"""Sync-free host-side span tracer with a bounded ring buffer.

The async training loop (training.py) and the decode engine
(generation/engine.py) deliberately keep the host off the device's
critical path; a tracer that synchronized — or even allocated without
bound — would undo exactly the overlap it is supposed to make visible
(T3, PAPERS.md: overlap is only tunable when it can be SEEN).  So this
module obeys two hard rules, enforced by the ``obs-no-sync`` graftcheck
rule (docs/guide/static-analysis.md): nothing in ``observability/`` may
touch the device, and every record is O(1) into a fixed-capacity ring
(old events drop, the hot path never blocks on I/O).

Usage::

    from megatron_llm_tpu.observability import trace

    trace.configure(capacity=65536)        # process-wide tracer, once
    with trace.span("data-wait", iteration=i):
        batch = next(loader)               # any thread
    trace.instant("step", iteration=i)
    trace.get_tracer().dump("trace_000010.json")   # Chrome trace JSON

When no tracer is configured (the default), ``span()`` returns a shared
null context and ``instant()`` is a no-op — the disabled cost is one
global read and one ``is None`` check.

The dump format is the Chrome/Perfetto ``traceEvents`` JSON (load it at
https://ui.perfetto.dev or chrome://tracing): complete ``"X"`` events
with microsecond ``ts``/``dur``, ``"i"`` instants, and thread-name
metadata rows so the driver / prefetch / checkpoint-writer / engine
threads come out labelled.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = [
    "SpanTracer",
    "configure",
    "disable",
    "get_tracer",
    "instant",
    "span",
]


class _NullContext:
    """Reusable no-op context: the disabled-tracer fast path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullContext()


class _Span:
    """Context manager recording one complete ("X") event on exit."""

    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str,
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._tracer._record("X", self._name, self._t0, t1 - self._t0,
                             self._args)
        return False


class SpanTracer:
    """Bounded in-memory event ring; thread-safe; never touches a device.

    Events are ``(ph, name, ts_s, dur_s, thread_ident, args)`` tuples with
    host ``time.perf_counter`` timestamps relative to the tracer's epoch.
    The ring holds the newest ``capacity`` events; older ones drop (the
    ``dropped`` counter keeps the tally honest in dumps).
    """

    def __init__(self, capacity: int = 65536, enabled: bool = True):
        self.capacity = max(int(capacity), 16)
        self.enabled = bool(enabled)
        self._epoch = time.perf_counter()
        self._buf: deque = deque(maxlen=self.capacity)  # guarded by _lock
        self._lock = threading.Lock()
        self._total = 0  # guarded by _lock
        # evictions, NOT reset by drain (honest dumps) — guarded by _lock
        self._dropped = 0

    # ---- recording (hot path) ----

    def span(self, name: str, **args) -> Any:
        """Context manager timing a named phase on the calling thread."""
        if not self.enabled:
            return _NULL
        return _Span(self, name, args or None)

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker event (step boundaries, triggers)."""
        if not self.enabled:
            return
        self._record("i", name, time.perf_counter(), 0.0, args or None)

    def _record(self, ph: str, name: str, t0: float, dur: float,
                args: Optional[Dict[str, Any]]) -> None:
        ident = threading.get_ident()
        with self._lock:
            if len(self._buf) == self.capacity:
                self._dropped += 1  # append below evicts the oldest
            self._buf.append((ph, name, t0 - self._epoch, dur, ident, args))
            self._total += 1

    # ---- inspection / export ----

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    @property
    def dropped(self) -> int:
        """Events pushed out of the ring by newer ones (drains — which
        consume events deliberately — do not count)."""
        with self._lock:
            return self._dropped

    def snapshot(self, drain: bool = False) -> List[tuple]:
        """A consistent copy of the ring (oldest first); optionally clears
        it, starting a fresh window."""
        with self._lock:
            events = list(self._buf)
            if drain:
                self._buf.clear()
            return events

    def to_chrome_trace(self, events: Optional[List[tuple]] = None) -> Dict:
        """Build the Chrome/Perfetto ``traceEvents`` document.

        Thread names are resolved from the live thread table at dump time
        (recording stores only the ident — name lookups are too slow for
        the hot path); threads that already exited keep their ident."""
        if events is None:
            events = self.snapshot()
        pid = os.getpid()
        names = {t.ident: t.name for t in threading.enumerate()}
        rows: List[Dict[str, Any]] = []
        seen_tids = set()
        for ph, name, ts, dur, tid, args in events:
            row: Dict[str, Any] = {
                "name": name, "ph": ph, "pid": pid, "tid": tid,
                "ts": round(ts * 1e6, 3),
            }
            if ph == "X":
                row["dur"] = round(dur * 1e6, 3)
            if args:
                row["args"] = args
            rows.append(row)
            seen_tids.add(tid)
        for tid in sorted(seen_tids):
            rows.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": names.get(tid, f"thread-{tid}")},
            })
        return {
            "traceEvents": rows,
            "displayTimeUnit": "ms",
            "otherData": {
                "dropped_events": self.dropped,
                "capacity": self.capacity,
            },
        }

    def dump(self, path: str, drain: bool = True) -> str:
        """Write a Chrome-trace JSON file atomically; returns ``path``.

        ``drain=True`` (the default) clears the ring, so successive dumps
        are disjoint N-step windows; ``drain=False`` leaves the ring
        intact (the watchdog's crash dump must not consume evidence)."""
        doc = self.to_chrome_trace(self.snapshot(drain=drain))
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path

    def write_text(self, stream, limit: int = 200) -> None:
        """Human-readable tail of the ring (newest last) — the watchdog's
        fallback when no trace dir is configured: a hang report should
        carry a timeline even without ``--trace_dir``."""
        events = self.snapshot()
        if not events:
            return
        print(f"TRACE: last {min(limit, len(events))} of {len(events)} "
              f"buffered events (dropped {self.dropped}):", file=stream)
        for ph, name, ts, dur, tid, args in events[-limit:]:
            extra = f" {args}" if args else ""
            if ph == "X":
                print(f"  {ts:12.6f}s +{dur * 1e3:9.3f}ms  {name} "
                      f"[tid {tid}]{extra}", file=stream)
            else:
                print(f"  {ts:12.6f}s     (mark)    {name} "
                      f"[tid {tid}]{extra}", file=stream)
        stream.flush()


# ---------------------------------------------------------------------------
# Process-wide tracer (the instrumented modules all share one)
# ---------------------------------------------------------------------------

_TRACER: Optional[SpanTracer] = None


def configure(capacity: int = 65536) -> SpanTracer:
    """Install (or replace) the process-wide tracer and return it."""
    global _TRACER
    _TRACER = SpanTracer(capacity=capacity, enabled=True)
    return _TRACER


def disable() -> None:
    """Drop the process-wide tracer: ``span()`` reverts to the null path."""
    global _TRACER
    _TRACER = None


def get_tracer() -> Optional[SpanTracer]:
    return _TRACER


def span(name: str, **args) -> Any:
    """Module-level span against the process-wide tracer (no-op context
    when none is configured) — what the instrumented hot paths call."""
    t = _TRACER
    if t is None or not t.enabled:
        return _NULL
    return _Span(t, name, args or None)


def instant(name: str, **args) -> None:
    t = _TRACER
    if t is None or not t.enabled:
        return
    t.instant(name, **args)
