"""Process-wide metrics registry with Prometheus text exposition.

One registry per process (``get_registry()``), fed from every subsystem:
``utils/timers.py`` Timers+Gauges mirror into it, the resilience goodput
tracker publishes its report, the training driver publishes throughput /
MFU, and the decode engine publishes tick/slot telemetry.  The exporter
(observability/exporter.py) renders it on ``GET /metrics`` in the
Prometheus text format (version 0.0.4), so a live job is scrapeable with
a stock Prometheus/Grafana stack.

Hot-path rules (the same contract as trace.py, lint-enforced): pure host
arithmetic, O(1) per update, a plain ``threading.Lock`` per instrument —
never any device work.  Publishing can be switched off process-wide
(``set_publishing(False)``) so the overhead benchmark
(bench_observability.py) can measure instrumented-vs-not honestly; the
instruments themselves keep working either way (``publishing()`` is the
gate the *publishers* check, not the registry).
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "GaugeMetric",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "publishing",
    "sanitize_metric_name",
    "set_publishing",
]

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_NAME_LEAD = re.compile(r"^[^a-zA-Z_:]")

# Prometheus histogram default buckets (seconds-flavored)
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   60.0, float("inf"))


def sanitize_metric_name(name: str) -> str:
    """Coerce an arbitrary name ('data-wait-ms') into the Prometheus
    grammar ``[a-zA-Z_:][a-zA-Z0-9_:]*`` ('data_wait_ms')."""
    name = _NAME_BAD.sub("_", name)
    if _NAME_LEAD.match(name):
        name = "_" + name
    return name


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label_value(s: str) -> str:
    return (s.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return f"{v:.10g}"


class _Instrument:
    __slots__ = ("_lock",)

    def __init__(self):
        self._lock = threading.Lock()


class Counter(_Instrument):
    """Monotonically increasing total."""

    __slots__ = ("_value",)

    def __init__(self):
        super().__init__()
        self._value = 0.0  # guarded by _lock

    def inc(self, v: float = 1.0) -> None:
        if v < 0:
            raise ValueError("counters only go up; use a gauge")
        with self._lock:
            self._value += v

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class GaugeMetric(_Instrument):
    """Last-written instantaneous value (may go up or down)."""

    __slots__ = ("_value",)

    def __init__(self):
        super().__init__()
        self._value = 0.0  # guarded by _lock

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def add(self, v: float) -> None:
        with self._lock:
            self._value += v

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram(_Instrument):
    """Cumulative-bucket histogram (Prometheus semantics)."""

    __slots__ = ("buckets", "_counts", "_sum", "_count")

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__()
        bs = sorted(float(b) for b in buckets)
        if not bs or bs[-1] != float("inf"):
            bs.append(float("inf"))
        self.buckets = tuple(bs)
        self._counts = [0] * len(self.buckets)  # guarded by _lock
        self._sum = 0.0    # guarded by _lock
        self._count = 0    # guarded by _lock

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    break

    def snapshot(self) -> Tuple[List[int], float, int]:
        """(cumulative per-bucket counts, sum, count)."""
        with self._lock:
            cum, acc = [], 0
            for c in self._counts:
                acc += c
                cum.append(acc)
            return cum, self._sum, self._count


class _Family:
    """All instruments sharing one metric name (distinct label sets)."""

    __slots__ = ("name", "kind", "help", "children")

    def __init__(self, name: str, kind: str, help_: str):
        self.name = name
        self.kind = kind
        self.help = help_
        # label tuple (sorted (k, v) pairs) -> instrument
        self.children: Dict[Tuple, _Instrument] = {}


class MetricsRegistry:
    """Thread-safe name -> instrument table with text exposition.

    ``counter`` / ``gauge`` / ``histogram`` are get-or-create: the first
    call fixes the type (and help text); a later call under a different
    type raises — one name, one meaning, as Prometheus requires.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}  # guarded by _lock

    # ---- get-or-create ----

    def _get(self, name: str, kind: str, help_: str,
             labels: Optional[Dict[str, str]], factory) -> _Instrument:
        name = sanitize_metric_name(name)
        key = tuple(sorted((k, str(v)) for k, v in (labels or {}).items()))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(name, kind, help_)
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    f"requested {kind}")
            inst = fam.children.get(key)
            if inst is None:
                inst = fam.children[key] = factory()
            return inst

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get(name, "counter", help, labels, Counter)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None) -> GaugeMetric:
        return self._get(name, "gauge", help, labels, GaugeMetric)

    def histogram(self, name: str, help: str = "",
                  labels: Optional[Dict[str, str]] = None,
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(name, "histogram", help, labels,
                         lambda: Histogram(buckets))

    # ---- introspection / tests ----

    def clear(self) -> None:
        with self._lock:
            self._families.clear()

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._families)

    # ---- exposition ----

    @staticmethod
    def _labels_text(key: Tuple, extra: str = "") -> str:
        parts = [f'{k}="{_escape_label_value(v)}"' for k, v in key]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def render(self) -> str:
        """The Prometheus text format (0.0.4): HELP/TYPE headers + one
        sample line per (labelset, series)."""
        with self._lock:
            families = [(f.name, f.kind, f.help, dict(f.children))
                        for f in self._families.values()]
        out: List[str] = []
        for name, kind, help_, children in sorted(families):
            if help_:
                out.append(f"# HELP {name} {_escape_help(help_)}")
            out.append(f"# TYPE {name} {kind}")
            for key in sorted(children):
                inst = children[key]
                if kind == "histogram":
                    cum, total, count = inst.snapshot()
                    for b, c in zip(inst.buckets, cum):
                        le = self._labels_text(key, f'le="{_fmt(b)}"')
                        out.append(f"{name}_bucket{le} {c}")
                    lt = self._labels_text(key)
                    out.append(f"{name}_sum{lt} {_fmt(total)}")
                    out.append(f"{name}_count{lt} {count}")
                else:
                    lt = self._labels_text(key)
                    out.append(f"{name}{lt} {_fmt(inst.value)}")
        return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# Process-wide registry + publisher switch
# ---------------------------------------------------------------------------

_REGISTRY = MetricsRegistry()
_PUBLISHING = True


def get_registry() -> MetricsRegistry:
    return _REGISTRY


def set_publishing(enabled: bool) -> None:
    """Switch the always-on publishers (timers, goodput, engine, driver)
    on/off process-wide — the bench_observability.py off-mode."""
    global _PUBLISHING
    _PUBLISHING = bool(enabled)


def publishing() -> bool:
    return _PUBLISHING
