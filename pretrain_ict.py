"""Pretrain a BERT biencoder on the Inverse Cloze Task.

Reference: /root/reference/pretrain_ict.py — builds the BiEncoder over ICT
data and trains with the in-batch contrastive loss (loss_func:76-118); the
retrieval accuracies print alongside the loss. The data path expects a
sentence-split indexed corpus (tools/preprocess_data.py --split_sentences)
and optionally a titles dataset (--titles_data_path).

    python pretrain_ict.py --data_path corpus_sent --titles_data_path titles \
        --num_layers 12 --hidden_size 768 --num_attention_heads 12 \
        --seq_length 256 --train_iters 10000 ...
"""

from __future__ import annotations

import jax

from megatron_llm_tpu.config import parse_args
from megatron_llm_tpu.data.ict_dataset import ICTDataset, ict_collator
from megatron_llm_tpu.data.indexed_dataset import make_dataset
from megatron_llm_tpu.data.samplers import build_pretraining_data_loader
from megatron_llm_tpu.retrieval.biencoder import (
    ict_loss_from_batch,
    init_biencoder_params,
)
from megatron_llm_tpu.training import pretrain


def _special_ids(tokenizer, vocab_size: int):
    def get(name, default):
        try:
            v = getattr(tokenizer, name, None)
            return int(v) if v is not None else default
        except NotImplementedError:
            return default

    return {
        "cls_id": get("cls", vocab_size - 4),
        "sep_id": get("sep", vocab_size - 3),
        "pad_id": get("pad", 0),
    }


def data_iterators_provider(cfg, tokenizer, consumed_samples):
    block_ds = make_dataset(cfg.data.data_path[0], cfg.data.data_impl)
    titles = None
    if cfg.retriever.titles_data_path:
        titles = make_dataset(cfg.retriever.titles_data_path, cfg.data.data_impl)
    ids = _special_ids(tokenizer, cfg.model.vocab_size)
    t = cfg.training

    num_train = max((t.train_iters or 0) * t.global_batch_size, 1)
    num_eval = max(t.eval_iters * t.global_batch_size, 1)

    def build(seed_offset, num_samples):
        return ICTDataset(
            block_ds, titles,
            max_seq_length=cfg.retriever.retriever_seq_length,
            query_in_block_prob=cfg.retriever.query_in_block_prob,
            seed=t.seed + seed_offset,
            use_titles=titles is not None,
            use_one_sent_docs=cfg.retriever.use_one_sent_docs,
            num_samples=num_samples,
            **ids,
        )

    def loader(ds, consumed):
        return build_pretraining_data_loader(
            ds, consumed, t.global_batch_size, cfg.data.dataloader_type,
            t.seed, collate_fn=ict_collator,
        )

    train_iter = loader(build(0, num_train), consumed_samples)
    valid_factory = lambda: loader(build(1, num_eval), 0)  # noqa: E731
    return train_iter, valid_factory


def main(argv=None):
    import sys

    argv = sys.argv[1:] if argv is None else argv
    if "--model_name" not in argv:
        argv = ["--model_name", "bert"] + argv
    cfg = parse_args(argv, n_devices=len(jax.devices()))
    # ICT trains the towers at retriever_seq_length
    cfg.data.seq_length = cfg.retriever.retriever_seq_length
    return pretrain(
        cfg,
        data_iterators_provider=data_iterators_provider,
        params_provider=lambda key: init_biencoder_params(cfg, key),
        loss_fn=ict_loss_from_batch,
    )


if __name__ == "__main__":
    main()
