"""Cross-replica KV page handoff (serving/handoff/, ISSUE 19).

Four layers, mirroring the subsystem: the wire format round-trips every
storage dtype byte-for-byte (scale rows and draft leaves included), the
engine export→import→re-export path is bit-identical with migrated
prefixes indistinguishable from locally cached ones (token/log-prob
parity + trie-hit proof), the replica kv_push endpoint's role/overload/
malformed-blob contract, and an end-to-end prefill+decode+unified fleet
behind the disagg router asserting routed responses are token-identical
to a unified replica with one trace id visible on every tier.
"""

import json
import time
import urllib.error
import urllib.request

import jax
import ml_dtypes
import numpy as np
import pytest

from megatron_llm_tpu.generation import EngineOverloaded
from megatron_llm_tpu.generation.engine import ContinuousBatchingEngine
from megatron_llm_tpu.generation.server import MegatronServer
from megatron_llm_tpu.models import init_model_params, make_config
from megatron_llm_tpu.serving.handoff import wire
from megatron_llm_tpu.serving.handoff.transfer import (
    KVPushError,
    push_pages,
)
from megatron_llm_tpu.serving.router.server import RouterServer

from tests.test_generation import VOCAB, ToyTokenizer

GREEDY = dict(top_k=1, use_eod_for_termination=False)
PS = 16  # the engines below keep the default page size


@pytest.fixture(scope="module")
def models():
    from megatron_llm_tpu.generation import DraftModel

    kw = dict(hidden_size=64, num_attention_heads=4,
              num_attention_heads_kv=2, ffn_hidden_size=128,
              vocab_size=VOCAB, seq_length=256,
              max_position_embeddings=256, hidden_dropout=0.0,
              attention_dropout=0.0, params_dtype="float32",
              use_flash_attn=False)
    cfg = make_config("llama2", num_layers=2, **kw)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    dcfg = make_config("llama2", num_layers=1, **kw)
    dparams = init_model_params(dcfg, jax.random.PRNGKey(1))
    return {"cfg": cfg, "params": params,
            "draft": DraftModel(dcfg, dparams)}


def _engine(models, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_seq", 256)
    return ContinuousBatchingEngine(models["cfg"], models["params"],
                                    ToyTokenizer(), **kw)


def _ids(n, seed=0):
    rng = np.random.default_rng(seed)
    return [int(t) for t in rng.integers(2, VOCAB, n)]


# ---------------------------------------------------------------------------
# Wire format
# ---------------------------------------------------------------------------


def _synthetic_leaves(kv_dtype, n_pages):
    rng = np.random.default_rng(7)
    shape = (2, n_pages, PS, 2, 16)
    if kv_dtype == "bf16":
        return {"k": rng.normal(size=shape).astype(ml_dtypes.bfloat16),
                "v": rng.normal(size=shape).astype(ml_dtypes.bfloat16)}
    q_dtype = (np.int8 if kv_dtype == "int8"
               else ml_dtypes.float8_e4m3fn)
    out = {}
    for name in ("k", "v"):
        out[f"{name}.q"] = rng.integers(
            -100, 100, shape).astype(q_dtype)
        out[f"{name}.scale"] = rng.uniform(
            1e-3, 1.0, (2, n_pages, 2)).astype(np.float32)
    return out


@pytest.mark.parametrize("kv_dtype", ["bf16", "int8", "fp8"])
def test_wire_roundtrip_byte_identity(kv_dtype):
    """encode→decode reproduces every leaf byte-for-byte — values,
    per-page scale rows, extended dtypes — and the header metadata the
    receiving trie keys on."""
    tokens = _ids(3 * PS)
    leaves = _synthetic_leaves(kv_dtype, 3)
    blob = wire.encode_pages(tokens, PS, kv_dtype, leaves)
    payload = wire.decode_pages(blob)
    assert payload.tokens == tokens
    assert payload.page_size == PS and payload.n_pages == 3
    assert payload.kv_dtype == kv_dtype
    assert set(payload.leaves) == set(leaves)
    for name, arr in leaves.items():
        got = payload.leaves[name]
        assert got.dtype == np.asarray(arr).dtype and got.shape == arr.shape
        assert got.tobytes() == np.ascontiguousarray(arr).tobytes(), name
    # and a re-encode of the decoded payload is the identical blob
    assert wire.encode_pages(payload.tokens, PS, kv_dtype,
                             payload.leaves) == blob


def test_wire_rejects_malformed():
    tokens = _ids(2 * PS)
    leaves = _synthetic_leaves("bf16", 2)
    blob = wire.encode_pages(tokens, PS, "bf16", leaves)
    with pytest.raises(ValueError, match="magic"):
        wire.decode_pages(b"XXXXXXXX" + blob[8:])
    with pytest.raises(ValueError, match="truncated"):
        wire.decode_pages(blob[:-10])
    with pytest.raises(ValueError, match="trailing"):
        wire.decode_pages(blob + b"\0")
    # sender-side invariants: page alignment and leaf page counts
    with pytest.raises(ValueError, match="page-aligned"):
        wire.encode_pages(tokens[:-1], PS, "bf16", leaves)
    with pytest.raises(ValueError, match="pages on axis 1"):
        wire.encode_pages(tokens, PS, "bf16",
                          {"k": leaves["k"][:, :1]})


# ---------------------------------------------------------------------------
# Engine export → import → re-export
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kv_dtype", ["bf16", "int8", "fp8"])
def test_export_import_reexport_bit_identical(models, kv_dtype):
    """The full migration path never re-quantizes: the receiver's
    re-export of an imported prefix is the sender's blob byte-for-byte,
    and decoding from the migrated pages is token- and log-prob-
    identical to prefilling locally, with the trie hit proving the
    migrated pages (not a recompute) served the prompt."""
    ids = _ids(5 * PS + 1)
    sender = _engine(models, kv_dtype=kv_dtype)
    blob, info = sender.prefill_and_export(ids, trace_id="exp")
    assert info["pages"] == 5 and info["tokens"] == 5 * PS
    assert info["bytes"] == len(blob)
    names = set(wire.decode_pages(blob).leaves)
    if kv_dtype == "bf16":
        assert names == {"k", "v"}
    else:
        assert names == {"k.q", "k.scale", "v.q", "v.scale"}

    receiver = _engine(models, kv_dtype=kv_dtype)
    receipt = receiver.import_kv(blob, trace_id="imp")
    assert receipt == {"pages": 5, "installed": 5, "deduped": 0,
                       "tokens": 5 * PS}
    blob2, n = receiver.export_cached_kv(ids[:5 * PS])
    assert n == 5 and blob2 == blob

    # migrated pages serve decode exactly like local prefill
    req = receiver.submit(ids, 12, trace_id="mig", **GREEDY)
    receiver.run_until_idle()
    got = req.result(timeout=120)
    fresh = _engine(models, kv_dtype=kv_dtype)
    ref = fresh.submit(ids, 12, **GREEDY)
    fresh.run_until_idle()
    assert got == ref.result(timeout=120)
    rec = receiver.flight.lookup("mig")[0]
    assert rec["hit_tokens"] == 5 * PS


def test_import_dedup_is_idempotent(models):
    """Re-pushing a blob costs nothing: trie incumbents win every
    position, the receipt says so, and the pool's free count is
    unchanged (release-after-insert leaves pages cached-idle)."""
    ids = _ids(4 * PS + 1, seed=3)
    sender = _engine(models)
    blob, _ = sender.prefill_and_export(ids)
    receiver = _engine(models)
    first = receiver.import_kv(blob)
    assert first["installed"] == 4 and first["deduped"] == 0
    free_after = len(receiver.pool._free)
    again = receiver.import_kv(blob)
    assert again == {"pages": 4, "installed": 0, "deduped": 4,
                     "tokens": 4 * PS}
    assert len(receiver.pool._free) == free_after


def test_import_rejects_incompatible_blobs(models):
    ids = _ids(3 * PS + 1, seed=4)
    sender = _engine(models)
    blob, _ = sender.prefill_and_export(ids)
    with pytest.raises(ValueError, match="page_size"):
        _engine(models, page_size=32).import_kv(blob)
    with pytest.raises(ValueError, match="kv_dtype"):
        _engine(models, kv_dtype="int8").import_kv(blob)
    with pytest.raises(ValueError, match="prefix cache"):
        _engine(models, prefix_cache=False).import_kv(blob)
    with pytest.raises(ValueError):
        sender.import_kv(b"not a handoff blob at all")


def test_import_overload_is_structured(models):
    """A pool that cannot hold the pushed pages answers EngineOverloaded
    with a drain hint — the sender degrades to unified serving instead
    of half-installing."""
    ids = _ids(5 * PS + 1, seed=5)
    blob, _ = _engine(models).prefill_and_export(ids)
    tiny = _engine(models, max_slots=1, num_pages=4)
    free_before = len(tiny.pool._free)
    with pytest.raises(EngineOverloaded) as ei:
        tiny.import_kv(blob)
    assert ei.value.retry_after > 0
    assert len(tiny.pool._free) == free_before  # nothing leaked


def test_spec_draft_leaves_ride_the_wire(models):
    """A speculating sender ships its draft-model KV alongside the
    target's; a speculating receiver re-exports it bit-identically; a
    non-speculating receiver refuses the blob (leaf mismatch) instead
    of silently dropping the draft pages."""
    ids = _ids(4 * PS + 1, seed=6)
    sender = _engine(models, spec_k=2, spec_draft=models["draft"])
    blob, info = sender.prefill_and_export(ids)
    assert info["pages"] == 4
    assert set(wire.decode_pages(blob).leaves) == {
        "k", "v", "draft_k", "draft_v"}
    receiver = _engine(models, spec_k=2, spec_draft=models["draft"])
    assert receiver.import_kv(blob)["installed"] == 4
    blob2, n = receiver.export_cached_kv(ids[:4 * PS])
    assert n == 4 and blob2 == blob
    with pytest.raises(ValueError, match="leaves"):
        _engine(models).import_kv(blob)


def test_preempted_request_migrates_token_identical(models):
    """The preempt→migrate→resume-elsewhere path: a preempted request's
    cached pages (prompt AND generated-so-far) export via
    export_cached_kv, install on a second engine, and the re-submitted
    request finishes token- and log-prob-identical to the sender's own
    bitwise resume — with the trie hit proving the migrated pages
    carried the resume."""
    ids = _ids(3 * PS, seed=8)
    sender = _engine(models, max_slots=1)
    victim = sender.submit(ids, 24, trace_id="victim", **GREEDY)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        sender.step()
        if victim._phase == "decode" and len(victim.generated) >= 8:
            break
    assert sender.preempt(victim)
    seq = ids + [int(t) for t in victim.generated]
    blob, n_pages = sender.export_cached_kv(seq)
    assert n_pages >= 3  # at least the full prompt pages migrated

    receiver = _engine(models)
    assert receiver.import_kv(blob)["pages"] == n_pages
    moved = receiver.submit(ids, 24, trace_id="moved", **GREEDY)
    receiver.run_until_idle()
    got = moved.result(timeout=120)

    sender.run_until_idle()  # the sender's own resume is the reference
    assert got == victim.result(timeout=120)
    assert receiver.flight.lookup("moved")[0]["hit_tokens"] > 0


def test_handoff_phase_decomposition_sums(models):
    """A prefill_only request's flight record lands in the ``handoff``
    phase bucket, carries the kv_export event, and its decomposition
    still partitions the measured latency exactly."""
    eng = _engine(models)
    eng.prefill_and_export(_ids(3 * PS + 1, seed=9), trace_id="hand")
    rec = eng.flight.lookup("hand")[0]
    assert rec["outcome"] == "handoff"
    assert rec["decomposition"]["handoff_s"] >= 0.0
    assert abs(sum(rec["decomposition"].values())
               - rec["latency_s"]) < 1e-5
    kinds = [e["kind"] for e in rec["events"]]
    assert "kv_export" in kinds


# ---------------------------------------------------------------------------
# Replica endpoint: POST /admin/kv_push + /health role
# ---------------------------------------------------------------------------


def _server(models, role, **ekw):
    srv = MegatronServer(_engine(models, **ekw), role=role)
    port = srv.start_background(port=0)
    return srv, f"http://127.0.0.1:{port}"


def _get_json(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read())


def test_kv_push_endpoint_contract(models):
    """Decode-role install → trie-hit serving; prefill-role refusal;
    malformed-blob 400; the advertised role in /health."""
    ids = _ids(5 * PS + 1, seed=10)
    blob, _ = _engine(models).prefill_and_export(ids)
    dec, dec_url = _server(models, "decode")
    pre, pre_url = _server(models, "prefill")
    try:
        assert _get_json(dec_url + "/health")["role"] == "decode"
        assert _get_json(pre_url + "/health")["role"] == "prefill"

        receipt = push_pages(dec_url, blob, trace_id="push-1")
        assert receipt["pages"] == 5 and receipt["installed"] == 5
        assert receipt["replica_id"] == dec.replica_id

        # a prefill-role replica is a KV sender, never a sink
        with pytest.raises(KVPushError) as ei:
            push_pages(pre_url, blob)
        assert ei.value.status == 400
        # bytes that are not a handoff blob are a 400, not a 500
        with pytest.raises(KVPushError) as ei:
            push_pages(dec_url, b"garbage bytes")
        assert ei.value.status == 400
    finally:
        dec.stop()
        pre.stop()
    with pytest.raises(ValueError, match="role"):
        MegatronServer(_engine(models), role="bogus")


def test_kv_push_overload_503_with_retry_after(models):
    ids = _ids(5 * PS + 1, seed=11)
    blob, _ = _engine(models).prefill_and_export(ids)
    srv, url = _server(models, "decode", max_slots=1, num_pages=4)
    try:
        with pytest.raises(KVPushError) as ei:
            push_pages(url, blob)
        assert ei.value.status == 503
        assert ei.value.retry_after is not None
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# End to end: prefill + decode + router vs a unified replica
# ---------------------------------------------------------------------------


def _put(url, payload, trace=None, timeout=600):
    hdrs = {"Content-Type": "application/json"}
    if trace:
        hdrs["X-MLT-Trace-Id"] = trace
    req = urllib.request.Request(
        url + "/api", data=json.dumps(payload).encode(),
        method="PUT", headers=hdrs)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.headers), json.loads(r.read())


def test_disagg_fleet_end_to_end(models):
    """A real 3-replica fleet over HTTP: long prompts route prefill →
    kv_push → decode through the disagg router and come back token- and
    log-prob-identical to a unified replica, under ONE trace id visible
    in all three tiers' flight recorders; the streamed variant matches
    too; short prompts skip the hop entirely."""
    pre, pre_url = _server(models, "prefill")
    dec, dec_url = _server(models, "decode")
    uni, uni_url = _server(models, "unified")
    router = RouterServer([pre_url, dec_url], policy="disagg",
                          policy_kwargs={"long_prompt_chars": 64},
                          poll_interval=0.25, forward_timeout_s=600.0)
    rurl = f"http://127.0.0.1:{router.start_background()}"
    long_prompt = "".join(chr(97 + (i * 7) % 26) for i in range(120))
    body = {"prompts": [long_prompt], "tokens_to_generate": 8,
            "top_k": 1, "random_seed": 1234}
    try:
        _, _, ref = _put(uni_url, body)

        st, hdrs, out = _put(rurl, body, trace="trace-e2e-1")
        assert st == 200 and hdrs.get("X-MLT-Trace-Id") == "trace-e2e-1"
        assert out["text"] == ref["text"]
        assert out["segments"] == ref["segments"]
        assert router._handoffs.value == 1
        assert router._handoff_failures.value == 0

        # the decode replica served the prompt from migrated pages
        assert _get_json(dec_url + "/health")["prefix_hit_tokens"] > 0
        # one trace id, three tiers
        q = "/debug/requests?trace_id=trace-e2e-1"
        fleet = _get_json(rurl + q)["fleet"]
        assert sum(v.get("count", 0) for v in fleet.values()) > 0
        assert _get_json(pre_url + q)["count"] > 0
        assert _get_json(dec_url + q)["count"] > 0

        # streamed through the same path: identical terminal body
        import http.client
        from urllib.parse import urlparse

        p = urlparse(rurl)
        conn = http.client.HTTPConnection(p.hostname, p.port, timeout=600)
        conn.request("PUT", "/api",
                     json.dumps({**body, "stream": True}).encode(),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        raw = resp.read().decode()
        conn.close()
        done = json.loads([ln for ln in raw.splitlines()
                           if ln.startswith("data:")][-1][5:])
        assert done["text"] == ref["text"]
        assert router._handoffs.value == 2

        # a short prompt never pays for the hop
        _put(rurl, {"prompts": ["hi"], "tokens_to_generate": 4,
                    "top_k": 1})
        assert router._handoffs.value == 2
    finally:
        router.stop()
        for s in (pre, dec, uni):
            s.stop()
