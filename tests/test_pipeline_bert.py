"""BERT under pipeline parallelism via the loss-agnostic hooks.

The reference's schedules are loss-agnostic through forward_step_func
(schedules.py:91 + pretrain_bert.py); our engine reaches the same generality
through pipeline_hooks (models/bert.py:bert_pipeline_hooks). These tests gate
that a pipelined BERT (1F1B, interleaved, GPipe) reproduces the unpipelined
computation: MLM CE (globally normalized) + sentence-order loss, with padding,
tokentypes, and the binary head all active.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.core.parallel_state import build_mesh, global_mesh
from megatron_llm_tpu.models import make_config
from megatron_llm_tpu.models.bert import (
    bert_forward,
    bert_pipeline_hooks,
    init_bert_params,
)
from megatron_llm_tpu.ops.cross_entropy import softmax_cross_entropy


def bert_cfg(pp=2, **kw):
    defaults = dict(
        num_layers=4,
        hidden_size=64,
        num_attention_heads=4,
        vocab_size=256,
        seq_length=32,
        max_position_embeddings=64,
        params_dtype="float32",
        micro_batch_size=2,
        global_batch_size=8,
        train_iters=5,
        use_flash_attn=False,
        pipeline_model_parallel_size=pp,
    )
    defaults.update(kw)
    cfg = make_config("bert", **defaults)
    cfg.parallel.num_micro_batches = 4
    return cfg


def bert_batch(cfg, key, gbs=8):
    s = cfg.data.seq_length
    ks = jax.random.split(key, 5)
    text = jax.random.randint(ks[0], (gbs, s), 0, cfg.model.vocab_size)
    labels = jax.random.randint(ks[1], (gbs, s), 0, cfg.model.vocab_size)
    # padding: last few positions of each row are pads
    lengths = jax.random.randint(ks[2], (gbs,), s - 6, s + 1)
    padding_mask = (jnp.arange(s)[None, :] < lengths[:, None]).astype(jnp.int32)
    # MLM positions: random 20% of REAL tokens
    loss_mask = (
        (jax.random.uniform(ks[3], (gbs, s)) < 0.2).astype(jnp.float32)
        * padding_mask
    )
    types = (jnp.arange(s)[None, :] >= (s // 2)).astype(jnp.int32) * padding_mask
    is_random = jax.random.bernoulli(ks[4], 0.5, (gbs,)).astype(jnp.int32)
    return {
        "text": text,
        "labels": labels,
        "loss_mask": loss_mask,
        "padding_mask": padding_mask,
        "types": types,
        "is_random": is_random,
    }


def reference_loss_fn(cfg, batch):
    """Unpipelined BERT loss with the pipeline's normalization (global MLM
    denominator, SOP summed over rows / gbs) — same math, additive-bias
    padding formulation."""
    denom = jnp.maximum(batch["loss_mask"].sum(), 1.0)
    gbs = batch["text"].shape[0]

    def f(params):
        lm_logits, binary_logits = bert_forward(
            cfg, params, batch["text"], batch["padding_mask"],
            tokentype_ids=batch["types"],
        )
        per_token = softmax_cross_entropy(lm_logits, batch["labels"])
        loss = (per_token * batch["loss_mask"]).sum() / denom
        logp = jax.nn.log_softmax(binary_logits.astype(jnp.float32), -1)
        sop = -jnp.take_along_axis(
            logp, batch["is_random"][:, None], axis=-1
        ).sum() / gbs
        return loss + sop

    return f


@pytest.mark.parametrize("schedule,vpp", [
    ("1f1b", 1),
    ("1f1b", 2),
    ("gpipe", 1),
])
def test_bert_pipeline_matches_unpipelined(schedule, vpp):
    cfg = bert_cfg(pp=2)
    cfg.parallel.pipeline_schedule = schedule
    cfg.parallel.virtual_pipeline_model_parallel_size = vpp if vpp > 1 else None
    params = init_bert_params(cfg, jax.random.PRNGKey(0))
    batch = bert_batch(cfg, jax.random.PRNGKey(1))

    ref = reference_loss_fn(cfg, batch)
    ref_loss, ref_grads = jax.value_and_grad(ref)(params)

    mesh = build_mesh(pipeline_model_parallel_size=2,
                      devices=jax.devices()[:2])
    pipe_batch, embed_fn, head_loss_fn = bert_pipeline_hooks(cfg, batch)
    with global_mesh(mesh):
        if schedule == "gpipe":
            from megatron_llm_tpu.parallel.pipeline import pipeline_loss_fn

            loss, grads = jax.jit(jax.value_and_grad(
                lambda p: pipeline_loss_fn(
                    cfg, mesh, p, pipe_batch, num_micro=4,
                    embed_fn=embed_fn, head_loss_fn=head_loss_fn,
                )[0]
            ))(params)
        elif vpp > 1:
            from megatron_llm_tpu.parallel.pipeline import (
                pipeline_1f1b_interleaved_loss_and_grads,
            )

            loss, grads = jax.jit(
                lambda p, b: pipeline_1f1b_interleaved_loss_and_grads(
                    cfg, mesh, p, b, num_micro=4,
                    embed_fn=embed_fn, head_loss_fn=head_loss_fn,
                )
            )(params, pipe_batch)
        else:
            from megatron_llm_tpu.parallel.pipeline import (
                pipeline_1f1b_loss_and_grads,
            )

            loss, grads = jax.jit(
                lambda p, b: pipeline_1f1b_loss_and_grads(
                    cfg, mesh, p, b, num_micro=4,
                    embed_fn=embed_fn, head_loss_fn=head_loss_fn,
                )
            )(params, pipe_batch)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(ref_grads)[0],
        jax.tree_util.tree_flatten_with_path(grads)[0],
    ):
        assert pa == pb
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=1e-4, atol=1e-5,
            err_msg=f"grad mismatch at {pa}",
        )


def test_bert_pipeline_train_step():
    """Full jitted train step with pipeline_hooks descends on a fixed batch."""
    from megatron_llm_tpu.models.bert import bert_loss_from_batch
    from megatron_llm_tpu.training_step import make_jitted_train_step

    cfg = bert_cfg(pp=2)
    mesh = build_mesh(pipeline_model_parallel_size=2)
    with global_mesh(mesh):
        params = init_bert_params(cfg, jax.random.PRNGKey(0))
        step, _o, sh = make_jitted_train_step(
            cfg, mesh, params, loss_fn=bert_loss_from_batch,
            pipeline_hooks=bert_pipeline_hooks,
        )
        batch = sh["place_batch"](
            {k: np.asarray(v) for k, v in
             bert_batch(cfg, jax.random.PRNGKey(1)).items()}
        )
        o = sh["opt_state_value"]
        p = params
        losses = []
        for i in range(4):
            p, o, m = step(p, o, batch, i)
            losses.append(float(m["lm loss"]))
            assert np.isfinite(losses[-1])
        assert losses[-1] < losses[0]
