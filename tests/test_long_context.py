"""Long-context smoke (VERDICT round-3 item 5): the 32K path's pieces —
RoPE position-interpolation scaling, long-seq masking, full remat, and the
ring-attention row-blocked online softmax — exercised end to end in a
train step at a CPU-tractable scaled-down width/seq. The full 32K e2e run
is bench.py --seq 32768 --rope_scaling 8 (tools/tpu_watch.py job
``bench_32k``); the AOT proof at real width is
tools/aot_scale_check.py::codellama_34b_32k.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu.core.parallel_state import build_mesh, global_mesh
from megatron_llm_tpu.models import init_model_params, make_config
from megatron_llm_tpu.training_step import make_jitted_train_step


def test_long_seq_rope_scaled_train_step():
    seq = 8192
    cfg = make_config(
        "codellama",  # theta=1e6 family bundle
        num_layers=2, hidden_size=128, num_attention_heads=4,
        num_attention_heads_kv=2, ffn_hidden_size=256, vocab_size=512,
        seq_length=seq, max_position_embeddings=seq,
        rope_scaling_factor=4.0, params_dtype="float32",
        micro_batch_size=1, global_batch_size=1, train_iters=10,
        use_flash_attn=False,
        context_parallel_size=2,  # ring attention carries the long seq
    )
    cfg.parallel.recompute_granularity = "full"
    cfg.finalize()
    mesh = build_mesh(context_parallel_size=2, devices=jax.devices()[:2])
    with global_mesh(mesh):
        params = init_model_params(cfg, jax.random.PRNGKey(0))
        step, _o, sh = make_jitted_train_step(cfg, mesh, params)
        tok = jax.random.randint(jax.random.PRNGKey(1), (1, seq + 1), 0, 512)
        batch = sh["place_batch"]({
            "tokens": tok[:, :-1], "labels": tok[:, 1:],
            "loss_mask": jnp.ones((1, seq), jnp.float32),
        })
        _p, _o2, m = step(params, sh["opt_state_value"], batch, 0)
        loss = float(m["lm loss"])
    assert np.isfinite(loss) and loss > 0, loss


def test_rope_scaling_changes_long_range_attention():
    """Position interpolation actually rescales positions: the rope cache
    for scaled positions at seq 8192 equals the unscaled cache at 2048
    stretched 4x (codellama 16K-native doubling semantics,
    reference positional_embeddings.py:11 scaling)."""
    from megatron_llm_tpu.models.language_model import make_rope_cache

    base = make_config(
        "codellama", num_layers=1, hidden_size=64, num_attention_heads=1,
        num_attention_heads_kv=1, vocab_size=64, seq_length=8192,
        max_position_embeddings=8192, params_dtype="float32",
        micro_batch_size=1, global_batch_size=1, train_iters=1)
    scaled = make_config(
        "codellama", num_layers=1, hidden_size=64, num_attention_heads=1,
        num_attention_heads_kv=1, vocab_size=64, seq_length=8192,
        max_position_embeddings=8192, rope_scaling_factor=4.0,
        params_dtype="float32",
        micro_batch_size=1, global_batch_size=1, train_iters=1)
    cb = make_rope_cache(base)
    cs = make_rope_cache(scaled)
    # scaled position p behaves like unscaled position p/4
    cb_f = jax.tree_util.tree_leaves(cb)[0]
    cs_f = jax.tree_util.tree_leaves(cs)[0]
    np.testing.assert_allclose(
        np.asarray(cs_f[4000]), np.asarray(cb_f[1000]), atol=1e-5)