"""Pipeline parallelism: pp>1 must match pp=1 numerics (reference analog:
loss-curve match requirement for the PP configs, SURVEY.md §7 step 8)."""

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu.core.parallel_state import build_mesh
from megatron_llm_tpu.models import init_model_params, make_config
from megatron_llm_tpu.training_step import make_jitted_train_step


def cfg_for(pp, tp=1, dp=1, num_micro=2, layers=4, vpp=1, dropout=0.0,
            schedule=None):
    gbs = 4
    cfg = make_config(
        "llama2",
        num_layers=layers,
        hidden_size=64,
        num_attention_heads=4,
        num_attention_heads_kv=2,
        vocab_size=256,
        seq_length=32,
        max_position_embeddings=64,
        params_dtype="float32",
        use_flash_attn=False,
        tensor_model_parallel_size=tp,
        pipeline_model_parallel_size=pp,
        micro_batch_size=gbs // num_micro,
        global_batch_size=gbs,
        train_iters=10,
        lr=1e-2,
    )
    cfg.parallel.data_parallel_size = dp
    cfg.parallel.num_micro_batches = num_micro
    if vpp > 1:
        cfg.parallel.virtual_pipeline_model_parallel_size = vpp
    if dropout:
        cfg.model.hidden_dropout = dropout
    if schedule:
        cfg.parallel.pipeline_schedule = schedule
    return cfg


def make_batch():
    tok = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0, 256)
    return {
        "tokens": np.asarray(tok[:, :-1]),
        "labels": np.asarray(tok[:, 1:]),
        "loss_mask": np.ones((4, 32), np.float32),
    }


def run_one_step(cfg, devices):
    mesh = build_mesh(
        tensor_model_parallel_size=cfg.parallel.tensor_model_parallel_size,
        pipeline_model_parallel_size=cfg.parallel.pipeline_model_parallel_size,
        devices=devices,
    )
    with mesh:
        params = init_model_params(cfg, jax.random.PRNGKey(0))
        step, _o, sh = make_jitted_train_step(cfg, mesh, params)
        p, _, m = step(params, sh["opt_state_value"], make_batch(), 0)
        return float(m["lm loss"]), jax.tree.map(np.asarray, p)


def test_pp2_matches_pp1(eight_devices):
    loss1, p1 = run_one_step(cfg_for(pp=1), eight_devices[:1])
    loss2, p2 = run_one_step(cfg_for(pp=2), eight_devices[:2])
    assert abs(loss1 - loss2) < 1e-4, (loss1, loss2)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_interleaved_pp2_v2_matches_pp1(eight_devices):
    """Virtual-pipeline (interleaved) schedule, ref schedules.py:253-502."""
    loss1, p1 = run_one_step(cfg_for(pp=1), eight_devices[:1])
    loss2, p2 = run_one_step(
        cfg_for(pp=2, vpp=2, schedule="gpipe"), eight_devices[:2])
    assert abs(loss1 - loss2) < 1e-4, (loss1, loss2)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_interleaved_pp4_v2_matches_pp1(eight_devices):
    loss1, p1 = run_one_step(cfg_for(pp=1, layers=8, num_micro=4),
                             eight_devices[:1])
    loss2, p2 = run_one_step(
        cfg_for(pp=4, layers=8, num_micro=4, vpp=2, schedule="gpipe"),
        eight_devices[:4])
    assert abs(loss1 - loss2) < 1e-4, (loss1, loss2)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_interleaved_1f1b_pp2_v2_matches_pp1(eight_devices):
    """True interleaved 1F1B: grads inside the tick loop with virtual
    stages (ref schedules.py:253-502)."""
    loss1, p1 = run_one_step(cfg_for(pp=1), eight_devices[:1])
    loss2, p2 = run_one_step(
        cfg_for(pp=2, vpp=2, schedule="1f1b"), eight_devices[:2])
    assert abs(loss1 - loss2) < 1e-4, (loss1, loss2)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_interleaved_1f1b_pp4_v2_matches_pp1(eight_devices):
    loss1, p1 = run_one_step(cfg_for(pp=1, layers=8, num_micro=4),
                             eight_devices[:1])
    loss2, p2 = run_one_step(
        cfg_for(pp=4, layers=8, num_micro=4, vpp=2, schedule="1f1b"),
        eight_devices[:4])
    assert abs(loss1 - loss2) < 1e-4, (loss1, loss2)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_interleaved_1f1b_multigroup_matches_pp1(eight_devices):
    """M > pp exercises the group arithmetic and ring-buffer recycling
    across groups (u//V grouping, slot reuse after 2V ticks)."""
    loss1, p1 = run_one_step(cfg_for(pp=1, num_micro=4), eight_devices[:1])
    loss2, p2 = run_one_step(
        cfg_for(pp=2, num_micro=4, vpp=2, schedule="1f1b"), eight_devices[:2])
    assert abs(loss1 - loss2) < 1e-4, (loss1, loss2)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_interleaved_1f1b_uneven_groups_matches_pp1(eight_devices):
    """M % pp != 0: the padded last group must mask correctly."""
    # gbs=4, num_micro=... M=3 needs gbs divisible by 3 — use layers=4 pp=2
    # with a 6-sample batch instead
    import jax as _jax

    cfg1 = cfg_for(pp=1, num_micro=3, layers=4)
    cfg1.training.global_batch_size = 6
    cfg1.training.micro_batch_size = 2
    cfg2 = cfg_for(pp=2, num_micro=3, layers=4, vpp=2, schedule="1f1b")
    cfg2.training.global_batch_size = 6
    cfg2.training.micro_batch_size = 2

    tok = _jax.random.randint(_jax.random.PRNGKey(1), (6, 33), 0, 256)
    batch = {
        "tokens": np.asarray(tok[:, :-1]),
        "labels": np.asarray(tok[:, 1:]),
        "loss_mask": np.ones((6, 32), np.float32),
    }

    def run(cfg, devs):
        mesh = build_mesh(
            pipeline_model_parallel_size=cfg.parallel.pipeline_model_parallel_size,
            devices=devs,
        )
        with mesh:
            params = init_model_params(cfg, jax.random.PRNGKey(0))
            step, _o, sh = make_jitted_train_step(cfg, mesh, params)
            p, _, m = step(params, sh["opt_state_value"], batch, 0)
            return float(m["lm loss"]), jax.tree.map(np.asarray, p)

    loss1, p1 = run(cfg1, eight_devices[:1])
    loss2, p2 = run(cfg2, eight_devices[:2])
    assert abs(loss1 - loss2) < 1e-4, (loss1, loss2)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_interleaved_1f1b_dropout_matches_pp1(eight_devices):
    loss1, p1 = run_one_step(cfg_for(pp=1, dropout=0.1), eight_devices[:1])
    loss2, p2 = run_one_step(
        cfg_for(pp=2, vpp=2, dropout=0.1, schedule="1f1b"), eight_devices[:2])
    assert abs(loss1 - loss2) < 1e-4, (loss1, loss2)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_bubble_fraction_interleaved_lower():
    from megatron_llm_tpu.parallel.pipeline import pipeline_bubble_fraction

    # at M = pp (the worst practical case) interleaving must cut the bubble
    for pp in (2, 4, 8):
        non = pipeline_bubble_fraction(pp, pp, 1)
        inter = pipeline_bubble_fraction(pp, pp, 2)
        assert inter < non, (pp, inter, non)
    assert abs(pipeline_bubble_fraction(4, 4, 1) - 3 / 7) < 1e-9
    assert abs(pipeline_bubble_fraction(4, 4, 2) - 3 / 11) < 1e-9


def test_gpipe_dropout_matches_pp1(eight_devices):
    """Per-microbatch dropout keys make pipelined dropout bit-identical to
    the pp=1 grad-accumulation path (VERDICT weak #4 lift)."""
    loss1, p1 = run_one_step(cfg_for(pp=1, dropout=0.1), eight_devices[:1])
    loss2, p2 = run_one_step(
        cfg_for(pp=2, dropout=0.1, schedule="gpipe"), eight_devices[:2])
    assert abs(loss1 - loss2) < 1e-4, (loss1, loss2)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_1f1b_dropout_matches_pp1(eight_devices):
    loss1, p1 = run_one_step(cfg_for(pp=1, dropout=0.1), eight_devices[:1])
    loss2, p2 = run_one_step(
        cfg_for(pp=2, dropout=0.1, schedule="1f1b"), eight_devices[:2])
    assert abs(loss1 - loss2) < 1e-4, (loss1, loss2)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_gpipe_ce_memory_bounded(eight_devices):
    """The pipelined path must never materialize the full [M, mb, s, vocab]
    logits (VERDICT weak #3): per-microbatch CE + remat keeps compiled temp
    memory well under the full-logits footprint at M=8, vocab 32k."""
    import jax.numpy as jnp

    M, mb, s, v = 8, 2, 128, 32000
    cfg = make_config(
        "llama2", num_layers=4, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, vocab_size=v, seq_length=s,
        max_position_embeddings=2 * s, params_dtype="float32",
        use_flash_attn=False, pipeline_model_parallel_size=2,
        micro_batch_size=mb, global_batch_size=M * mb, train_iters=10, lr=1e-2,
    )
    cfg.parallel.num_micro_batches = M
    cfg.parallel.pipeline_schedule = "gpipe"
    mesh = build_mesh(pipeline_model_parallel_size=2, devices=eight_devices[:2])
    with mesh:
        params = init_model_params(cfg, jax.random.PRNGKey(0))
        step, _o, sh = make_jitted_train_step(cfg, mesh, params)
        tok = jnp.zeros((M * mb, s + 1), jnp.int32)
        batch = {"tokens": tok[:, :-1], "labels": tok[:, 1:],
                 "loss_mask": jnp.ones((M * mb, s), jnp.float32)}
        ma = step.lower(params, sh["opt_state_value"], batch, 0) \
                 .compile().memory_analysis()
    full_logits_bytes = M * mb * s * v * 4
    assert ma.temp_size_in_bytes < full_logits_bytes, (
        f"temp {ma.temp_size_in_bytes / 2**20:.0f} MiB >= full-logits "
        f"{full_logits_bytes / 2**20:.0f} MiB: CE is materializing the batch"
    )


def test_pp4_with_tp2_matches_pp1(eight_devices):
    loss1, p1 = run_one_step(cfg_for(pp=1), eight_devices[:1])
    loss2, p2 = run_one_step(cfg_for(pp=4, tp=2, num_micro=4), eight_devices[:8])
    assert abs(loss1 - loss2) < 1e-4, (loss1, loss2)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)
