"""TP/SP correctness on the 8-device CPU mesh.

Analog of the reference's distributed-unit tests (tests/tensor_parallel/,
megatron/mpu/tests/test_layers.py:506 — sharded layers match the unsharded
reference numerics) but runnable without accelerators.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from megatron_llm_tpu.core.parallel_state import build_mesh
from megatron_llm_tpu.models import init_model_params, make_config, model_forward
from megatron_llm_tpu.parallel.tp import param_shardings, make_sp_constraint
from megatron_llm_tpu.training_step import make_jitted_train_step


def tiny_config(tp=1, sp=False, dp=None, **kw):
    defaults = dict(
        num_layers=2,
        hidden_size=64,
        num_attention_heads=4,
        num_attention_heads_kv=2,
        vocab_size=256,
        seq_length=32,
        max_position_embeddings=64,
        params_dtype="float32",
        use_flash_attn=False,
        tensor_model_parallel_size=tp,
        sequence_parallel=sp,
    )
    defaults.update(kw)
    cfg = make_config("llama2", **defaults)
    if dp is not None:
        cfg.parallel.data_parallel_size = dp
    return cfg


@pytest.mark.parametrize("tp,sp", [(2, False), (4, False), (4, True), (8, True)])
def test_tp_forward_matches_single_device(eight_devices, tp, sp):
    """Sharded logits must equal single-device logits (same params)."""
    cfg1 = tiny_config()
    params = init_model_params(cfg1, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 256)
    ref_logits, _ = model_forward(cfg1, params, tokens)

    cfgN = tiny_config(tp=tp, sp=sp)
    mesh = build_mesh(tensor_model_parallel_size=tp,
                      devices=eight_devices[: max(tp, 8 if sp else tp)])
    with mesh:
        shardings = param_shardings(mesh, params)
        sharded_params = jax.device_put(params, shardings)
        sp_c = make_sp_constraint(cfgN)

        @jax.jit
        def fwd(p, t):
            out, _ = model_forward(cfgN, p, t, sp_constraint=sp_c)
            return out

        tp_logits = fwd(sharded_params, tokens)
    np.testing.assert_allclose(
        np.asarray(ref_logits), np.asarray(tp_logits), atol=2e-4, rtol=2e-4
    )


def test_tp_forward_qwen2_qkv_bias(eight_devices):
    """Qwen2's QKV-only bias under tensor parallelism: the fused bias
    shards column-parallel with the kernel (parallel/tp.py qkv bias rule);
    a wrong spec would offset the wrong heads' logits."""
    base = dict(num_layers=2, hidden_size=64, num_attention_heads=4,
                num_attention_heads_kv=2, vocab_size=256, seq_length=32,
                max_position_embeddings=64, params_dtype="float32",
                use_flash_attn=False)
    cfg1 = make_config("qwen2", **base)
    params = init_model_params(cfg1, jax.random.PRNGKey(0))
    # non-zero bias so a mis-sharded bias actually changes the logits
    qkv = params["layers"]["attention"]["qkv"]
    qkv["bias"] = jax.random.normal(
        jax.random.PRNGKey(7), qkv["bias"].shape, qkv["bias"].dtype) * 0.1
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 256)
    ref_logits, _ = model_forward(cfg1, params, tokens)

    cfgN = make_config("qwen2", **base, tensor_model_parallel_size=2)
    mesh = build_mesh(tensor_model_parallel_size=2, devices=eight_devices[:2])
    with mesh:
        sharded = jax.device_put(params, param_shardings(mesh, params))
        tp_logits, _ = jax.jit(
            lambda p, t: model_forward(cfgN, p, t))(sharded, tokens)
    np.testing.assert_allclose(
        np.asarray(ref_logits), np.asarray(tp_logits), atol=2e-4, rtol=2e-4)


def test_train_step_tp_dp_matches_single(eight_devices):
    """One full train step on tp=2 x dp=4 must match single-device numerics."""
    tok = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, 256)
    batch = {
        "tokens": np.asarray(tok[:, :-1]),
        "labels": np.asarray(tok[:, 1:]),
        "loss_mask": np.ones((8, 32), np.float32),
    }

    losses = {}
    params_after = {}
    for name, (tp, dp, zero1) in {
        "single": (1, 1, False),
        "tp2dp4": (2, 4, True),
    }.items():
        cfg = tiny_config(tp=tp, dp=dp, sp=(tp > 1),
                          use_distributed_optimizer=zero1,
                          micro_batch_size=8 // dp, global_batch_size=8,
                          train_iters=10, lr=1e-2)
        cfg.parallel.num_micro_batches = 1
        devs = eight_devices[: tp * dp]
        mesh = build_mesh(tensor_model_parallel_size=tp, devices=devs)
        with mesh:
            params = init_model_params(cfg, jax.random.PRNGKey(0))
            step, _opt, sh = make_jitted_train_step(cfg, mesh, params)
            p, o, m = step(params, sh["opt_state_value"], batch, 0)
            losses[name] = float(m["lm loss"])
            params_after[name] = jax.tree.map(np.asarray, p)

    assert abs(losses["single"] - losses["tp2dp4"]) < 1e-4, losses
    flat1 = jax.tree_util.tree_leaves(params_after["single"])
    flat2 = jax.tree_util.tree_leaves(params_after["tp2dp4"])
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_zero1_sharded_fraction(eight_devices):
    """The dp-sharding heuristic must cover nearly all optimizer state —
    silently-replicated moments would defeat ZeRO-1 (VERDICT weak #7)."""
    from megatron_llm_tpu.optimizer.optimizer import (
        get_optimizer,
        zero1_sharded_fraction,
    )

    cfg = tiny_config(tp=2, dp=4, sp=True, use_distributed_optimizer=True,
                      micro_batch_size=2, global_batch_size=8,
                      train_iters=10, lr=1e-2)
    mesh = build_mesh(tensor_model_parallel_size=2, devices=eight_devices)
    with mesh:
        params = init_model_params(cfg, jax.random.PRNGKey(0))
        opt_state = get_optimizer(cfg, params).init(params)
        frac = zero1_sharded_fraction(cfg, params, opt_state, dp_size=4)
    # moments dominate element counts; norm scales may stay replicated but
    # must be a sliver
    assert frac > 0.95, f"only {frac:.1%} of optimizer state is dp-sharded"


def test_microbatch_accumulation_matches_full_batch(eight_devices):
    """num_micro_batches=4 grads == one big batch (pure accumulation)."""
    tok = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, 256)
    batch = {
        "tokens": np.asarray(tok[:, :-1]),
        "labels": np.asarray(tok[:, 1:]),
        "loss_mask": np.ones((8, 32), np.float32),
    }
    results = {}
    for nm in (1, 4):
        cfg = tiny_config(micro_batch_size=8 // nm, global_batch_size=8,
                          train_iters=10, lr=1e-2)
        cfg.parallel.num_micro_batches = nm
        mesh = build_mesh(devices=eight_devices[:1])
        with mesh:
            params = init_model_params(cfg, jax.random.PRNGKey(0))
            step, _o, sh = make_jitted_train_step(cfg, mesh, params)
            p, _, m = step(params, sh["opt_state_value"], batch, 0)
            results[nm] = (float(m["lm loss"]), jax.tree.map(np.asarray, p))
    assert abs(results[1][0] - results[4][0]) < 1e-5
    for a, b in zip(jax.tree_util.tree_leaves(results[1][1]),
                    jax.tree_util.tree_leaves(results[4][1])):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)
