"""End-to-end training driver test: toy corpus -> pretrain() -> checkpoint ->
resume (reference analog: the test_llama_weights.py lifecycle test, minus the
real weights)."""

import os

import numpy as np
import pytest

from megatron_llm_tpu.config import Config, apply_architecture
from megatron_llm_tpu.data.indexed_dataset import make_builder


@pytest.fixture
def toy_corpus(tmp_path):
    prefix = str(tmp_path / "corpus_text_document")
    rng = np.random.RandomState(0)
    builder = make_builder(prefix + ".bin", vocab_size=500)
    for _ in range(50):
        builder.add_doc(rng.randint(1, 500, size=rng.randint(40, 120)))
    builder.finalize(prefix + ".idx")
    return prefix


def small_cfg(toy_corpus, tmp_path, train_iters=8):
    cfg = Config()
    apply_architecture(cfg, "llama2")
    cfg.model.num_layers = 2
    cfg.model.hidden_size = 64
    cfg.model.num_attention_heads = 4
    cfg.model.num_attention_heads_kv = 2
    cfg.model.vocab_size = 512
    cfg.model.max_position_embeddings = 64
    cfg.data.seq_length = 32
    cfg.data.data_path = [toy_corpus]
    cfg.data.tokenizer_type = "NullTokenizer"
    cfg.training.params_dtype = "float32"
    cfg.training.use_flash_attn = False
    cfg.training.micro_batch_size = 4
    cfg.training.global_batch_size = 4
    cfg.training.train_iters = train_iters
    cfg.training.eval_iters = 2
    cfg.training.eval_interval = 4
    cfg.optimizer.lr = 1e-3
    cfg.optimizer.lr_warmup_iters = 2
    cfg.checkpoint.save = str(tmp_path / "ckpt")
    cfg.checkpoint.save_interval = 4
    cfg.logging.log_interval = 4
    cfg.finalize(n_devices=1)
    return cfg


def test_pretrain_end_to_end_and_resume(toy_corpus, tmp_path, capsys):
    from megatron_llm_tpu.training import pretrain

    cfg = small_cfg(toy_corpus, tmp_path, train_iters=8)
    result = pretrain(cfg)
    assert result["iteration"] == 8
    assert result["consumed_samples"] == 32
    first_loss = float(result["last_metrics"]["lm loss"])
    assert np.isfinite(first_loss)
    # checkpoint layout
    ckpt = cfg.checkpoint.save
    assert os.path.isfile(os.path.join(ckpt, "latest_checkpointed_iteration.txt"))
    assert os.path.isdir(os.path.join(ckpt, "iter_0000008", "params"))

    # ---- resume: 8 more iterations from the checkpoint ----
    cfg2 = small_cfg(toy_corpus, tmp_path, train_iters=16)
    cfg2.checkpoint.load = ckpt
    result2 = pretrain(cfg2)
    assert result2["iteration"] == 16
    assert result2["consumed_samples"] == 64
    second_loss = float(result2["last_metrics"]["lm loss"])
    assert second_loss < 6.5  # training is actually progressing

    out = capsys.readouterr().out
    assert "validation loss" in out
    assert "tokens/sec" in out


def test_profiler_and_span_breakdown(toy_corpus, tmp_path, capsys):
    """--profile dumps an xplane trace; timing_log_level>=2 prints the
    fwd/bwd/opt split (SURVEY §5 / VERDICT missing #6, weak #8)."""
    from megatron_llm_tpu.training import pretrain

    cfg = small_cfg(toy_corpus, tmp_path, train_iters=6)
    cfg.checkpoint.save = None
    cfg.logging.profile = True
    cfg.logging.profile_step_start = 2
    cfg.logging.profile_step_end = 4
    cfg.logging.profile_dir = str(tmp_path / "prof")
    cfg.logging.timing_log_level = 2
    cfg.logging.log_interval = 4
    result = pretrain(cfg)
    assert result["iteration"] == 6

    out = capsys.readouterr().out
    assert "xplane trace written" in out
    assert "span breakdown" in out and "backward" in out
    # the trace directory must contain an .xplane.pb dump
    found = []
    for root, _dirs, files in os.walk(tmp_path / "prof"):
        found += [f for f in files if f.endswith(".xplane.pb")]
    assert found, "no xplane trace file written"


def test_finetune_flag_resets_iteration(toy_corpus, tmp_path):
    from megatron_llm_tpu.training import pretrain

    cfg = small_cfg(toy_corpus, tmp_path, train_iters=4)
    pretrain(cfg)

    cfg2 = small_cfg(toy_corpus, tmp_path, train_iters=2)
    cfg2.checkpoint.load = cfg.checkpoint.save
    cfg2.checkpoint.finetune = True
    cfg2.checkpoint.save = str(tmp_path / "ckpt2")
    result = pretrain(cfg2)
    assert result["iteration"] == 2  # reset, not resumed at 4


def test_observability_flags(toy_corpus, tmp_path, capsys):
    """log_num_zeros_in_grad / log_params_norm / log_memory flags are live
    (reference training_log surface, training.py:462-641)."""
    from megatron_llm_tpu.training import pretrain

    cfg = small_cfg(toy_corpus, tmp_path, train_iters=4)
    cfg.checkpoint.save = None
    cfg.logging.log_num_zeros_in_grad = True
    cfg.logging.log_params_norm = True
    cfg.logging.log_memory_to_tensorboard = True
    cfg.logging.tensorboard_dir = str(tmp_path / "tb")
    cfg.logging.log_interval = 2
    result = pretrain(cfg)
    assert result["iteration"] == 4
    assert "num_zeros" in result["last_metrics"]
    assert float(result["last_metrics"]["params_norm"]) > 0
    out = capsys.readouterr().out
    assert "num zeros:" in out and "params norm:" in out
