"""Multi-host runtime pieces (core/distributed.py) — testable single-host by
mocking process topology; the real cross-host path is exercised by the same
code because jax.make_array_from_process_local_data degenerates to
device_put semantics at process_count == 1."""

from unittest import mock

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.core.distributed import (
    place_host_local_batch,
    process_batch_slice,
)
from megatron_llm_tpu.data.samplers import (
    MegatronPretrainingSampler,
    _ProcessSlicedSampler,
    build_pretraining_data_loader,
)


def test_process_batch_slice_partitions_the_batch():
    with mock.patch.object(jax, "process_count", return_value=4):
        slices = []
        for pid in range(4):
            with mock.patch.object(jax, "process_index", return_value=pid):
                slices.append(process_batch_slice(16))
    assert slices == [(0, 4), (4, 8), (8, 12), (12, 16)]
    # rows cover the batch exactly once, in order (matches the contiguous
    # row-major (dp, ep) batch sharding)
    covered = [i for a, b in slices for i in range(a, b)]
    assert covered == list(range(16))


def test_process_batch_slice_requires_divisibility():
    with mock.patch.object(jax, "process_count", return_value=3):
        with pytest.raises(AssertionError):
            process_batch_slice(16)


def test_process_sliced_sampler_keeps_global_bookkeeping():
    base = MegatronPretrainingSampler(
        total_samples=32, consumed_samples=8, global_batch_size=8
    )
    sliced = _ProcessSlicedSampler(base, 2, 4)  # host 1 of 4, 2 rows each
    batches = list(iter(sliced))
    # same number of global batches, each reduced to this host's rows
    assert len(batches) == 3
    assert batches[0] == [10, 11]  # rows 2:4 of global batch [8..16)
    assert batches[1] == [18, 19]
    assert batches[2] == [26, 27]


def test_loader_process_sliced_single_process_is_identity():
    class DS:
        def __len__(self):
            return 16

        def __getitem__(self, i):
            return {"x": np.full((4,), i, np.int32)}

    it = build_pretraining_data_loader(
        DS(), 0, 8, "single", process_sliced=True
    )
    batch = next(iter(it))
    assert batch["x"].shape == (8, 4)
    np.testing.assert_array_equal(batch["x"][:, 0], np.arange(8))


def test_place_host_local_batch_single_process_matches_device_put():
    from jax.sharding import NamedSharding, PartitionSpec as P

    from megatron_llm_tpu.core.parallel_state import build_mesh

    mesh = build_mesh(devices=jax.devices()[:4])
    sh = {"tokens": NamedSharding(mesh, P(("dp", "ep"), None)),
          "token_idx": NamedSharding(mesh, P(None))}
    batch = {"tokens": np.arange(32).reshape(4, 8),
             "token_idx": np.arange(8)}
    placed = place_host_local_batch(batch, sh)
    np.testing.assert_array_equal(np.asarray(placed["tokens"]),
                                  batch["tokens"])
    assert placed["tokens"].sharding.spec == P(("dp", "ep"), None)
    np.testing.assert_array_equal(np.asarray(placed["token_idx"]),
                                  batch["token_idx"])


def test_initialize_distributed_single_host_noop():
    from megatron_llm_tpu.core import distributed

    distributed._INITIALIZED = False
    distributed.initialize_distributed()  # must not raise or hang
    assert distributed._INITIALIZED


_WORKER = r"""
import os, sys
pid = int(sys.argv[1]); tmp = sys.argv[2]; port = sys.argv[3]
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=4"
os.environ["MEGATRON_COORDINATOR"] = "127.0.0.1:" + port
os.environ["MEGATRON_NUM_PROCESSES"] = "2"
os.environ["MEGATRON_PROCESS_ID"] = str(pid)

import numpy as np
from megatron_llm_tpu.core.distributed import initialize_distributed
initialize_distributed()
import jax
assert jax.process_count() == 2
assert len(jax.devices()) == 8

from megatron_llm_tpu.config import Config, apply_architecture
from megatron_llm_tpu.data.indexed_dataset import make_builder
from megatron_llm_tpu.training import pretrain
import time

prefix = os.path.join(tmp, "corpus_text_document")
ready = os.path.join(tmp, "data_ready")
if pid == 0:
    rng = np.random.RandomState(0)
    b = make_builder(prefix + ".bin", vocab_size=250)
    for _ in range(60):
        b.add_doc(rng.randint(1, 250, size=rng.randint(30, 80)))
    b.finalize(prefix + ".idx")
    open(ready, "w").write("1")
else:
    while not os.path.exists(ready):
        time.sleep(0.2)

cfg = Config()
apply_architecture(cfg, "llama2")
cfg.model.num_layers = 2; cfg.model.hidden_size = 64
cfg.model.num_attention_heads = 4; cfg.model.num_attention_heads_kv = 2
cfg.model.vocab_size = 256; cfg.model.max_position_embeddings = 64
cfg.data.seq_length = 32; cfg.data.data_path = [prefix]
cfg.data.tokenizer_type = "NullTokenizer"
cfg.training.params_dtype = "float32"; cfg.training.use_flash_attn = False
cfg.training.micro_batch_size = 2; cfg.training.global_batch_size = 8
cfg.training.train_iters = 4; cfg.training.eval_iters = 1
cfg.training.eval_interval = 2; cfg.logging.log_interval = 2
cfg.parallel.tensor_model_parallel_size = 2
cfg.checkpoint.save = os.path.join(tmp, "ckpt"); cfg.checkpoint.save_interval = 4
cfg.finalize(n_devices=8)

result = pretrain(cfg)
loss = float(result["last_metrics"]["lm loss"])
assert result["iteration"] == 4 and np.isfinite(loss)
print("WORKER_OK", pid, loss, flush=True)
"""


def test_two_process_pretrain_end_to_end(tmp_path):
    """REAL multi-process training: two OS processes, 4 virtual CPU devices
    each, jax.distributed over a localhost coordinator (gloo collectives),
    process-sliced data loading, dp x tp mesh spanning both processes,
    eval, and a multi-process orbax checkpoint save. Both processes must
    finish with the SAME loss (lockstep SPMD)."""
    import os
    import socket
    import subprocess
    import sys

    with socket.socket() as s:  # free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])

    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), str(tmp_path), port],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    losses = [
        line.split()[2]
        for out in outs for line in out.splitlines()
        if line.startswith("WORKER_OK")
    ]
    assert len(losses) == 2 and losses[0] == losses[1], losses
    assert (tmp_path / "ckpt" / "iter_0000004").is_dir()
