"""Resilience subsystem (ISSUE 3): verified checkpoints + atomic commit,
corruption quarantine + fallback load, prune safety, hang watchdog,
supervised auto-restart with bitwise-identical resume, goodput accounting,
and prompt background-thread shutdown.  CPU-only, tier-1-fast."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_llm_tpu.config import Config
from megatron_llm_tpu.resilience import goodput as gp
from megatron_llm_tpu.resilience import integrity
from megatron_llm_tpu.resilience.supervisor import (
    RestartPolicy,
    Supervisor,
    classify_exit,
)
from megatron_llm_tpu.resilience.watchdog import EXIT_WATCHDOG, StepWatchdog


def _cfg(keep=None):
    cfg = Config()
    cfg.checkpoint.keep_last_n_checkpoints = keep
    cfg.finalize(n_devices=1)
    return cfg


def _params():
    import jax.numpy as jnp

    return {"w": jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
            "b": jnp.full((8,), 0.5, jnp.float32)}


def _save(cfg, d, it, consumed=None):
    from megatron_llm_tpu.checkpointing import save_checkpoint

    save_checkpoint(cfg, d, it, _params(),
                    consumed_samples=consumed if consumed is not None
                    else it * 4)


def _flip_byte(ckpt_dir, offset=4):
    """Corrupt one manifested file in place (size preserved -> sha catch)."""
    for dirpath, _d, files in os.walk(ckpt_dir):
        for name in files:
            p = os.path.join(dirpath, name)
            if name != integrity.MANIFEST_FILENAME and os.path.getsize(p) > 8:
                with open(p, "r+b") as f:
                    f.seek(offset)
                    b = f.read(1)
                    f.seek(offset)
                    f.write(bytes([b[0] ^ 0xFF]))
                return p
    raise AssertionError(f"no file to corrupt under {ckpt_dir}")


# ---------------------------------------------------------------------------
# integrity: manifest + verify + quarantine
# ---------------------------------------------------------------------------


def test_save_writes_verifying_manifest(tmp_path):
    from megatron_llm_tpu.checkpointing import checkpoint_dir

    d = str(tmp_path / "ckpt")
    cfg = _cfg()
    _save(cfg, d, 3)
    path = checkpoint_dir(d, 3)
    assert integrity.has_manifest(path)
    ok, problems = integrity.verify_checkpoint(path)
    assert ok, problems
    m = integrity.read_manifest(path)
    assert m["iteration"] == 3
    assert m["config_fingerprint"] == integrity.config_fingerprint(cfg)
    assert m["num_files"] == len(m["files"]) > 0
    # no tmp dir left behind
    assert not any(n.endswith(integrity.TMP_SUFFIX)
                   for n in os.listdir(d))


def test_verify_detects_bitflip_truncation_missing(tmp_path):
    from megatron_llm_tpu.checkpointing import checkpoint_dir

    d = str(tmp_path / "ckpt")
    _save(_cfg(), d, 1)
    path = checkpoint_dir(d, 1)

    victim = _flip_byte(path)
    ok, problems = integrity.verify_checkpoint(path)
    assert not ok and any("sha256 mismatch" in p for p in problems)

    with open(victim, "r+b") as f:  # truncate
        f.truncate(2)
    ok, problems = integrity.verify_checkpoint(path)
    assert not ok and any("size mismatch" in p for p in problems)

    os.remove(victim)
    ok, problems = integrity.verify_checkpoint(path)
    assert not ok and any("missing file" in p for p in problems)


def test_quarantine_and_listing(tmp_path):
    from megatron_llm_tpu.checkpointing import checkpoint_dir

    d = str(tmp_path / "ckpt")
    cfg = _cfg()
    for it in (1, 2):
        _save(cfg, d, it)
    bad = integrity.quarantine(checkpoint_dir(d, 1))
    assert bad.endswith(integrity.CORRUPT_SUFFIX)
    os.makedirs(checkpoint_dir(d, 5) + integrity.TMP_SUFFIX)
    # quarantined + tmp dirs never count as committed checkpoints
    assert integrity.list_checkpoint_iterations(d) == [2]
    # repeated quarantine of the same iteration gets a fresh name
    _save(cfg, d, 1)
    bad2 = integrity.quarantine(checkpoint_dir(d, 1))
    assert bad2 != bad and os.path.isdir(bad2)


def test_tracker_only_advances_past_verified_manifest(tmp_path, monkeypatch):
    """Commit-ordering satellite: a crash between the orbax write and the
    manifest leaves the tracker at the PREVIOUS checkpoint (no referenced
    torn checkpoint), and the half-written tmp dir is reclaimed by the
    next save."""
    import megatron_llm_tpu.checkpointing as ck

    d = str(tmp_path / "ckpt")
    cfg = _cfg()
    _save(cfg, d, 1)
    assert ck.read_tracker(d) == (1, False)

    def boom(*a, **k):
        raise OSError("simulated crash before manifest")

    monkeypatch.setattr(ck._integ, "write_manifest", boom)
    with pytest.raises(OSError, match="simulated crash"):
        _save(cfg, d, 2)
    monkeypatch.undo()
    assert ck.read_tracker(d) == (1, False)  # tracker never moved
    assert integrity.list_checkpoint_iterations(d) == [1]  # only .tmp for 2
    _save(cfg, d, 2)  # next save reclaims the stale tmp dir
    assert ck.read_tracker(d) == (2, False)
    assert integrity.verify_checkpoint(ck.checkpoint_dir(d, 2))[0]


def test_async_save_goes_through_manifest_commit(tmp_path):
    from megatron_llm_tpu.checkpointing import (
        AsyncCheckpointSaver,
        checkpoint_dir,
    )

    d = str(tmp_path / "ckpt")
    saver = AsyncCheckpointSaver()
    saver.save(_cfg(), d, 7, _params(), consumed_samples=28)
    saver.wait()
    assert integrity.verify_checkpoint(checkpoint_dir(d, 7))[0]


# ---------------------------------------------------------------------------
# load: verified fallback walk + quarantine
# ---------------------------------------------------------------------------


def test_load_falls_back_to_previous_verified(tmp_path):
    from megatron_llm_tpu.checkpointing import (
        checkpoint_dir,
        load_checkpoint,
        read_tracker,
    )

    d = str(tmp_path / "ckpt")
    cfg = _cfg()
    _save(cfg, d, 2, consumed=8)
    _save(cfg, d, 4, consumed=16)
    _flip_byte(checkpoint_dir(d, 4))

    params, _opt, it, consumed, _meta = load_checkpoint(cfg, d, _params())
    assert (it, consumed) == (2, 8)
    np.testing.assert_array_equal(np.asarray(params["w"]),
                                  np.asarray(_params()["w"]))
    # the torn checkpoint is out of the resume path, bytes kept
    assert not os.path.isdir(checkpoint_dir(d, 4))
    assert os.path.isdir(checkpoint_dir(d, 4) + integrity.CORRUPT_SUFFIX)
    # load never rewrites the tracker; the next SAVE does
    assert read_tracker(d) == (4, False)


def test_load_survives_tracker_pointing_at_missing_dir(tmp_path):
    """The pre-resilience failure shape: tracker references bytes that
    never became durable.  Load must walk back instead of crashing."""
    import shutil

    from megatron_llm_tpu.checkpointing import (
        _write_tracker,
        checkpoint_dir,
        load_checkpoint,
    )

    d = str(tmp_path / "ckpt")
    cfg = _cfg()
    _save(cfg, d, 2, consumed=8)
    shutil.rmtree(checkpoint_dir(d, 4), ignore_errors=True)
    _write_tracker(d, 4)  # referenced checkpoint does not exist
    _p, _o, it, consumed, _m = load_checkpoint(cfg, d, _params())
    assert (it, consumed) == (2, 8)


def test_load_all_corrupt_raises(tmp_path):
    from megatron_llm_tpu.checkpointing import checkpoint_dir, load_checkpoint

    d = str(tmp_path / "ckpt")
    cfg = _cfg()
    _save(cfg, d, 2)
    _flip_byte(checkpoint_dir(d, 2))
    with pytest.raises(FileNotFoundError, match="failed manifest"):
        load_checkpoint(cfg, d, _params())
    assert os.path.isdir(checkpoint_dir(d, 2) + integrity.CORRUPT_SUFFIX)


def test_load_accepts_tracked_legacy_checkpoint(tmp_path):
    """Pre-manifest checkpoints (old repo state) still load when the
    tracker names them — the upgrade path must not strand existing runs."""
    from megatron_llm_tpu.checkpointing import checkpoint_dir, load_checkpoint

    d = str(tmp_path / "ckpt")
    cfg = _cfg()
    _save(cfg, d, 3, consumed=12)
    os.remove(integrity.manifest_path(checkpoint_dir(d, 3)))
    _p, _o, it, consumed, _m = load_checkpoint(cfg, d, _params())
    assert (it, consumed) == (3, 12)


def test_verify_on_load_off_restores_legacy_behavior(tmp_path):
    from megatron_llm_tpu.checkpointing import checkpoint_dir, load_checkpoint

    d = str(tmp_path / "ckpt")
    cfg = _cfg()
    _save(cfg, d, 2)
    _flip_byte(checkpoint_dir(d, 2))
    cfg.checkpoint.verify_on_load = False
    # no verification: the corrupt bytes load "successfully" (orbax may or
    # may not notice) or raise — but nothing is quarantined either way
    try:
        load_checkpoint(cfg, d, _params())
    except Exception:
        pass
    assert os.path.isdir(checkpoint_dir(d, 2))


# ---------------------------------------------------------------------------
# prune safety
# ---------------------------------------------------------------------------


def test_prune_skips_corrupt_and_protects_newest_verified(tmp_path):
    from megatron_llm_tpu.checkpointing import _prune_old, checkpoint_dir

    d = str(tmp_path / "ckpt")
    cfg = _cfg()  # no pruning during setup saves
    for it in (2, 4, 6, 8):
        _save(cfg, d, it)
    # a quarantined dir is present and must not crash the iteration parse
    # (the old split("_") did) nor be touched
    integrity.quarantine(checkpoint_dir(d, 8))
    # 4 and 6 rot on disk; 2 is the only good resume point left
    _flip_byte(checkpoint_dir(d, 4))
    _flip_byte(checkpoint_dir(d, 6))

    cfg.checkpoint.keep_last_n_checkpoints = 1
    _prune_old(cfg, d, latest=6)
    # keep=1 would normally leave only 6 — but 2 is the newest VERIFIED
    # checkpoint and must survive; 4 (corrupt, unquarantined) is fair game
    left = sorted(os.listdir(d))
    assert os.path.isdir(checkpoint_dir(d, 2)), left
    assert os.path.isdir(checkpoint_dir(d, 6)), left
    assert not os.path.isdir(checkpoint_dir(d, 4)), left
    assert any(n.startswith("iter_0000008" + integrity.CORRUPT_SUFFIX)
               for n in left)


def test_prune_normal_window(tmp_path):
    from megatron_llm_tpu.checkpointing import _prune_old, checkpoint_dir

    d = str(tmp_path / "ckpt")
    cfg = _cfg(keep=2)
    for it in (1, 2, 3):
        _save(cfg, d, it)  # save itself prunes: keep=2 -> {2, 3}
    assert integrity.list_checkpoint_iterations(d) == [2, 3]
    _prune_old(cfg, d, latest=3)  # idempotent
    assert integrity.list_checkpoint_iterations(d) == [2, 3]


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------


def _make_wd(**kw):
    import io

    stream = io.StringIO()
    exits = []
    calls = {"gauge": 0, "snapshot": 0}
    defaults = dict(
        multiplier=2.0, min_deadline=0.2, first_deadline=0.3,
        snapshot_timeout=1.0, stream=stream,
        exit_fn=lambda code: exits.append(code),
        gauge_fn=lambda: calls.__setitem__("gauge", calls["gauge"] + 1),
        snapshot_fn=lambda: calls.__setitem__(
            "snapshot", calls["snapshot"] + 1),
    )
    defaults.update(kw)
    wd = StepWatchdog(**defaults).start()
    return wd, stream, exits, calls


def test_watchdog_trips_with_dump_gauge_snapshot_and_code():
    wd, stream, exits, calls = _make_wd()
    wd.arm(first=True)  # 0.3s deadline
    deadline = time.time() + 10
    while not exits and time.time() < deadline:
        time.sleep(0.02)
    assert exits == [EXIT_WATCHDOG]
    assert wd.expired
    out = stream.getvalue()
    assert "WATCHDOG" in out and "thread stacks" in out
    assert "step-watchdog" in out or "MainThread" in out  # real stacks
    assert calls["gauge"] == 1 and calls["snapshot"] == 1


def test_watchdog_disarm_prevents_trip_and_feeds_ema():
    wd, _stream, exits, _calls = _make_wd(min_deadline=0.2)
    for _ in range(3):
        wd.arm()
        wd.disarm(step_time=0.01)
    time.sleep(0.6)
    assert exits == [] and not wd.expired
    # EMA fed with 10ms steps: steady deadline floors at min_deadline
    assert wd.current_deadline() == pytest.approx(0.2)
    wd._ema = 1.0
    assert wd.current_deadline() == pytest.approx(2.0)  # multiplier x EMA
    assert wd.current_deadline(first=True) == pytest.approx(0.3)
    wd.stop()
    assert not wd._thread.is_alive()


def test_watchdog_snapshot_timeout_still_exits():
    """An emergency snapshot that hangs (wedged device) must not block the
    exit — that would recreate the hang the watchdog exists to break."""
    wd, stream, exits, _calls = _make_wd(
        snapshot_fn=lambda: time.sleep(60), snapshot_timeout=0.2)
    t0 = time.time()
    wd.arm()  # no EMA -> first/min deadline
    deadline = time.time() + 10
    while not exits and time.time() < deadline:
        time.sleep(0.02)
    assert exits == [EXIT_WATCHDOG]
    assert time.time() - t0 < 5.0
    assert "did not finish" in stream.getvalue()


# ---------------------------------------------------------------------------
# goodput
# ---------------------------------------------------------------------------


def test_goodput_report_math():
    t0 = 1000.0
    g = gp.GoodputTracker(t0)
    g.run_started(resumed_iteration=10, prev_progress_iteration=14)
    assert g.replayed_steps == 4
    g.record_compile(5.0)
    g.record_productive(steps=20, seconds=40.0)  # 2s/step
    rep = g.report(now=t0 + 60.0)
    assert rep["lost_replay_seconds"] == pytest.approx(8.0)  # 4 x 2s
    assert rep["productive_seconds"] == pytest.approx(32.0)
    assert rep["productive_steps"] == 16
    assert rep["lost_compile_seconds"] == 5.0
    assert rep["other_seconds"] == pytest.approx(15.0)  # 60 - 40 - 5
    assert rep["goodput_fraction"] == pytest.approx(32.0 / 60.0, abs=1e-3)


def test_goodput_progress_roundtrip_and_aggregate(tmp_path):
    d = str(tmp_path)
    assert gp.read_progress(d) is None
    gp.write_progress(d, 42)
    assert gp.read_progress(d) == 42
    gp.write_progress(d, 43)
    assert gp.read_progress(d) == 43
    assert gp.read_progress(None) is None

    agg = gp.aggregate_reports([
        {"wall_seconds": 100.0, "productive_seconds": 80.0,
         "productive_steps": 40, "lost_compile_seconds": 10.0,
         "lost_replay_seconds": 4.0},
        {"wall_seconds": 50.0, "productive_seconds": 45.0,
         "productive_steps": 20, "lost_compile_seconds": 5.0,
         "lost_replay_seconds": 0.0},
        None,
    ], downtime_seconds=10.0)
    assert agg["wall_seconds"] == pytest.approx(160.0)
    assert agg["productive_seconds"] == pytest.approx(125.0)
    assert agg["productive_steps"] == 60
    assert agg["lost_restart_seconds"] == 10.0
    assert agg["goodput_fraction"] == pytest.approx(125.0 / 160.0, abs=1e-3)


def test_pretrain_result_carries_goodput(tmp_path):
    """The driver reports goodput on every run and persists it next to the
    checkpoints (save/resilience) for the supervisor."""
    from test_training_driver import small_cfg

    from megatron_llm_tpu.training import pretrain

    corpus = tmp_path / "corpus_text_document"
    rng = np.random.RandomState(0)
    from megatron_llm_tpu.data.indexed_dataset import make_builder

    builder = make_builder(str(corpus) + ".bin", vocab_size=500)
    for _ in range(50):
        builder.add_doc(rng.randint(1, 500, size=rng.randint(40, 120)))
    builder.finalize(str(corpus) + ".idx")

    cfg = small_cfg(str(corpus), tmp_path, train_iters=4)
    result = pretrain(cfg)
    rep = result["goodput"]
    assert rep["wall_seconds"] > 0
    assert rep["productive_steps"] == 3  # 4 steps minus the compile step
    assert 0.0 <= rep["goodput_fraction"] <= 1.0
    resil = os.path.join(cfg.checkpoint.save, "resilience")
    assert gp.read_report(resil)["productive_steps"] == 3
    assert gp.read_progress(resil) == 4  # log_interval=4 high-water mark


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------


def test_classify_exit_taxonomy():
    assert classify_exit(0) == "clean"
    assert classify_exit(EXIT_WATCHDOG) == "hang"
    assert classify_exit(-9) == "signal"
    assert classify_exit(-15) == "signal"
    assert classify_exit(1) == "crash"
    assert classify_exit(77) == "crash"


def test_restart_policy_backoff():
    p = RestartPolicy(backoff_base=2.0, backoff_max=30.0)
    assert [p.next_delay(n) for n in (1, 2, 3, 4, 5)] == [
        2.0, 4.0, 8.0, 16.0, 30.0]  # capped


def test_supervisor_restarts_until_clean(tmp_path):
    """Two crashes, then success (a counter file drives the script); the
    state json records the attempt history and aggregate goodput."""
    counter = tmp_path / "n"
    script = (
        "import sys, pathlib; p = pathlib.Path(r'%s');"
        "n = int(p.read_text()) if p.exists() else 0;"
        "p.write_text(str(n + 1));"
        "sys.exit(0 if n >= 2 else 7)" % counter
    )
    sup = Supervisor([sys.executable, "-c", script], str(tmp_path / "resil"),
                     policy=RestartPolicy(max_restarts=5, backoff_base=0.05,
                                          backoff_max=0.1),
                     install_signal_handlers=False)
    assert sup.run() == 0
    state = sup.load_state()
    assert [a["class"] for a in state["attempts"]] == [
        "crash", "crash", "clean"]
    assert state["restarts_used"] == 2
    assert state["final"] == "clean exit"
    assert "aggregate_goodput" in state


def test_supervisor_budget_exhausted(tmp_path):
    sup = Supervisor([sys.executable, "-c", "import sys; sys.exit(3)"],
                     str(tmp_path / "resil"),
                     policy=RestartPolicy(max_restarts=2, backoff_base=0.02,
                                          backoff_max=0.05),
                     install_signal_handlers=False)
    rc = sup.run()
    assert rc == 3
    state = sup.load_state()
    assert len(state["attempts"]) == 3  # initial + 2 restarts
    assert "budget exhausted" in state["final"]


def test_supervisor_sigterm_forwarding_no_restart(tmp_path):
    """Graceful preemption: SIGTERM forwards to the child (which exits
    cleanly here) and the supervisor does NOT restart."""
    script = ("import signal, sys, time;"
              "signal.signal(signal.SIGTERM, lambda *a: sys.exit(0));"
              "time.sleep(60)")
    sup = Supervisor([sys.executable, "-c", script], str(tmp_path / "resil"),
                     policy=RestartPolicy(max_restarts=5, backoff_base=0.05),
                     install_signal_handlers=False, term_grace=10.0)
    out = {}

    def run():
        out["rc"] = sup.run()

    t = threading.Thread(target=run)
    t.start()
    deadline = time.time() + 15
    while sup.child_pid is None and time.time() < deadline:
        time.sleep(0.05)
    assert sup.child_pid is not None
    time.sleep(0.3)  # let the child install its handler
    sup.request_stop()
    t.join(timeout=15)
    assert not t.is_alive()
    assert out["rc"] == 0
    assert len(sup.load_state()["attempts"]) == 1  # no restart


# ---------------------------------------------------------------------------
# prompt shutdown of background data threads
# ---------------------------------------------------------------------------


def test_prefetcher_close_unblocks_source_pull():
    """A worker blocked inside next(source) — a loader stalled forever —
    must not wedge close(): close propagates to the source and the join
    stays bounded (the satellite fix; the watchdog abort path relies on
    teardown never hanging)."""
    from megatron_llm_tpu.data.prefetch import BatchPrefetcher
    from megatron_llm_tpu.data.samplers import DataIterator

    class SlowDataset:
        def __len__(self):
            return 10**6

        def __getitem__(self, i):
            if i >= 4:
                time.sleep(3600)  # dead filesystem
            return {"x": np.full((2,), i, np.int32)}

    class Seq:
        def __iter__(self):
            for i in range(10**6):
                yield [i]

    src = DataIterator(SlowDataset(), Seq(), prefetch=2)
    pf = BatchPrefetcher(src, depth=2)
    assert next(pf)[1]["x"].flat[0] == 0  # stream is live
    t0 = time.time()
    pf.close()
    assert time.time() - t0 < 10.0
    assert pf.closed
    assert not pf._thread.is_alive()  # worker unblocked via source close
    with pytest.raises(StopIteration):
        next(pf)


def test_dataiterator_close_idempotent_and_consumer_safe():
    from megatron_llm_tpu.data.samplers import DataIterator

    class DS:
        def __len__(self):
            return 100

        def __getitem__(self, i):
            return {"x": np.full((2,), i, np.int32)}

    class Seq:
        def __iter__(self):
            for i in range(100):
                yield [i]

    it = DataIterator(DS(), Seq(), prefetch=2)
    assert next(it)["x"].flat[0] == 0
    it.close()
    it.close()  # idempotent
    assert not it._thread.is_alive()
    with pytest.raises(StopIteration):  # consumer never blocks after close
        next(it)


def test_sampler_resume_exact_and_end_of_data():
    from megatron_llm_tpu.data.samplers import (
        MegatronPretrainingRandomSampler,
        MegatronPretrainingSampler,
    )

    full = list(MegatronPretrainingSampler(40, 0, 4))
    resumed = list(MegatronPretrainingSampler(40, 16, 4))
    assert resumed == full[4:]  # identical batch sequence after resume

    # cyclic sampler: resume mid-epoch and across the epoch boundary
    ref = MegatronPretrainingRandomSampler(20, 0, 4, seed=7)
    it = iter(ref)
    stream = [next(it) for _ in range(9)]  # crosses into epoch 2
    res = iter(MegatronPretrainingRandomSampler(20, 16, 4, seed=7))
    assert [next(res) for _ in range(5)] == stream[4:]

    # resume AT data end is a valid state, not an assert crash
    done = MegatronPretrainingSampler(40, 40, 4)
    assert len(done) == 0 and list(done) == []


# ---------------------------------------------------------------------------
# chaos round-trips (acceptance): subprocess children via the smoke tool
# ---------------------------------------------------------------------------


def _smoke():
    import tools.resilience_smoke as rs

    return rs


@pytest.fixture(scope="module")
def chaos_corpus(tmp_path_factory):
    workdir = str(tmp_path_factory.mktemp("chaos"))
    return workdir, _smoke().build_corpus(workdir)


def test_chaos_kill9_resume_bitwise(chaos_corpus):
    """ISSUE 3 acceptance: a supervisor-managed run SIGKILLed mid-training
    auto-resumes from the newest verified checkpoint and reproduces the
    uninterrupted run's loss trajectory bitwise on every post-resume
    iteration."""
    workdir, corpus = chaos_corpus
    out = _smoke().phase_chaos(workdir, corpus)
    assert out["ok"], out
    assert out["bitwise_identical"]
    assert out["attempt_classes"][0] == "signal"  # the SIGKILL
    assert out["attempt_classes"][-1] == "clean"
    # the resumed attempt restarted from a committed checkpoint (not from
    # scratch) and re-ran the killed step and everything after it
    assert out["resumed_after_iteration"] >= 2
    assert len(out["compared_iterations"]) >= 3
    assert 0.0 < out["goodput_fraction"] <= 1.0
    # state file survives for post-mortem
    state_path = os.path.join(workdir, "resil", "resilience_state.json")
    with open(state_path) as f:
        state = json.load(f)
    assert state["final"] == "clean exit"


def test_chaos_hang_trips_watchdog(chaos_corpus):
    """A silently hung step exits with the distinct watchdog code and a
    stack dump, within the configured deadline."""
    workdir, corpus = chaos_corpus
    out = _smoke().phase_hang(workdir, corpus)
    assert out["ok"], out
    assert out["rc"] == EXIT_WATCHDOG
    assert out["stack_dump"]
