"""Vision data (data/vision.py): AutoAugment ImageNet policy + class-folder
dataset — the rebuild of the reference's last descoped modules
(megatron/data/autoaugment.py, image_folder.py)."""

from __future__ import annotations

import numpy as np
import pytest

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402

from megatron_llm_tpu.data.vision import (  # noqa: E402
    IMAGENET_POLICY,
    ImageFolder,
    ImageNetPolicy,
    _RANGES,
    _apply_op,
    find_classes,
    is_image_file,
)


def _img(seed=0, size=(32, 32)):
    rng = np.random.default_rng(seed)
    return Image.fromarray(
        rng.integers(0, 256, (*size, 3), dtype=np.uint8), "RGB")


def test_policy_table_is_the_published_one():
    assert len(IMAGENET_POLICY) == 25
    ops = {op for p in IMAGENET_POLICY for op in (p[0], p[3])}
    assert ops <= set(_RANGES)
    # spot-check published entries (paper Table 9 / reference :76-101)
    assert IMAGENET_POLICY[0] == ("posterize", 0.4, 8, "rotate", 0.6, 9)
    assert IMAGENET_POLICY[18] == ("shearX", 0.6, 5, "equalize", 1.0, 9)


def test_all_14_ops_apply():
    img = _img()
    for op, rng in _RANGES.items():
        out = _apply_op(img, op, rng[5], 1, (128, 128, 128))
        assert out.size == img.size and out.mode == "RGB", op


def test_policy_deterministic_under_seeded_rng():
    img = _img(1)
    a = ImageNetPolicy(rng=np.random.default_rng(7))(img)
    b = ImageNetPolicy(rng=np.random.default_rng(7))(img)
    assert np.array_equal(np.asarray(a), np.asarray(b))
    # different stream: across 10 draws at least one must differ from the
    # seed-7 output, else the policy is ignoring its rng
    pol8 = ImageNetPolicy(rng=np.random.default_rng(8))
    assert any(
        not np.array_equal(np.asarray(pol8(img)), np.asarray(a))
        for _ in range(10))


def test_policy_changes_images():
    """Across many draws the policy must actually augment (non-identity)."""
    img = _img(2)
    pol = ImageNetPolicy(rng=np.random.default_rng(3))
    changed = sum(
        not np.array_equal(np.asarray(pol(img)), np.asarray(img))
        for _ in range(20))
    assert changed >= 10, changed


@pytest.fixture()
def image_tree(tmp_path):
    for ci, cls in enumerate(["ants", "bees", "cats", "dogs"]):
        d = tmp_path / cls
        d.mkdir()
        for i in range(4):
            _img(ci * 10 + i, (8, 8)).save(d / f"{i}.png")
        (d / "notes.txt").write_text("not an image")
    return tmp_path


def test_image_folder_discovery(image_tree):
    ds = ImageFolder(str(image_tree))
    assert ds.classes == ["ants", "bees", "cats", "dogs"]
    assert len(ds) == 16
    sample, target = ds[0]
    assert sample.shape == (8, 8, 3) and sample.dtype == np.uint8
    assert target == 0
    assert is_image_file("x.JPG") and not is_image_file("x.txt")


def test_image_folder_fractions(image_tree):
    """The reference's classes_fraction / data_per_class_fraction knobs
    (image_folder.py:33,67,109)."""
    ds = ImageFolder(str(image_tree), classes_fraction=0.5,
                     data_per_class_fraction=0.5)
    assert ds.classes == ["ants", "bees"]
    assert len(ds) == 4  # 2 classes x 2 of 4 images
    assert set(ds.targets) == {0, 1}


def test_image_folder_transform_pipeline(image_tree):
    """transform hook: AutoAugment -> numpy, the training-pipeline shape."""
    pol = ImageNetPolicy(rng=np.random.default_rng(0))
    ds = ImageFolder(str(image_tree),
                     transform=lambda im: np.asarray(pol(im), np.float32) / 255.0,
                     target_transform=lambda t: t + 100)
    sample, target = ds[5]
    assert sample.dtype == np.float32 and sample.max() <= 1.0
    assert target >= 100


def test_image_folder_empty_raises(tmp_path):
    (tmp_path / "empty_class").mkdir()
    with pytest.raises(FileNotFoundError):
        ImageFolder(str(tmp_path))


def test_image_folder_corrupt_sample_recovery(image_tree):
    """A corrupt file substitutes a random sample (image_folder.py:215-221)
    instead of killing the epoch; an all-corrupt tree raises clearly."""
    bad = image_tree / "ants" / "0.png"
    bad.write_bytes(b"not a png")
    ds = ImageFolder(str(image_tree))
    idx = ds.samples.index((str(bad), 0))
    sample, target = ds[idx]  # must not raise
    assert sample.shape == (8, 8, 3)

    ds.loader = lambda path: (_ for _ in ()).throw(OSError("always fails"))
    with pytest.raises(RuntimeError, match="every sample"):
        ds[0]


def test_find_classes_fraction_floor(image_tree):
    classes, mapping = find_classes(str(image_tree), classes_fraction=0.1)
    assert classes == ["ants"]  # never fewer than one class
    assert mapping == {"ants": 0}
