"""HF logit parity through weight conversion (the reference's flagship
test_llama_weights.py lifecycle, with random-init tiny HF models instead of
real weights — zero-egress friendly)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from megatron_llm_tpu.models import make_config, model_forward
from verify_correctness import verify
from weights_conversion.hf_to_native import (
    config_from_hf,
    convert_hf_model,
)


def tiny_hf_llama(nkv=2, vocab=128):
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=vocab, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=nkv,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False, attn_implementation="eager",
    )
    torch.manual_seed(0)
    return LlamaForCausalLM(cfg)


def tiny_hf_mistral():
    from transformers import MistralConfig, MistralForCausalLM

    cfg = MistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rms_norm_eps=1e-5, sliding_window=32,
        tie_word_embeddings=False, attn_implementation="eager",
    )
    torch.manual_seed(1)
    return MistralForCausalLM(cfg)


@pytest.mark.parametrize("nkv", [4, 2])
def test_llama_logit_parity(nkv):
    hf = tiny_hf_llama(nkv=nkv)
    cfg = config_from_hf(hf.config, "llama2")
    cfg.training.params_dtype = "float32"
    cfg.training.use_flash_attn = False
    stats = verify(hf, cfg, batch_size=2, seq=48, iters=2)
    avg_max = np.mean([s[2] for s in stats])
    # reference gate: avg per-token max abs err <= 1e-3 (test_llama_weights.py:117)
    assert avg_max <= 1e-3, f"avg max logit err {avg_max}"


def test_mistral_logit_parity_sliding_window():
    hf = tiny_hf_mistral()
    cfg = config_from_hf(hf.config, "mistral")
    cfg.training.params_dtype = "float32"
    cfg.training.use_flash_attn = False
    assert cfg.model.sliding_window_size == 32
    # seq > window so the window actually matters
    stats = verify(hf, cfg, batch_size=1, seq=96, iters=2)
    avg_max = np.mean([s[2] for s in stats])
    assert avg_max <= 1e-3, f"avg max logit err {avg_max}"


def test_hf_round_trip():
    """native -> HF -> logits identical to the original HF model."""
    _round_trip(tiny_hf_llama(nkv=2), "llama2", "to_hf_llama_state")


def tiny_hf_falcon():
    from transformers import FalconConfig, FalconForCausalLM

    fc = FalconConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_kv_heads=2, new_decoder_architecture=True,
        parallel_attn=True, bias=False, alibi=False,
        max_position_embeddings=128, attn_implementation="eager",
    )
    torch.manual_seed(2)
    return FalconForCausalLM(fc)


def test_falcon_logit_parity():
    hf = tiny_hf_falcon()
    cfg = config_from_hf(hf.config, "falcon")
    cfg.training.params_dtype = "float32"
    cfg.training.use_flash_attn = False
    stats = verify(hf, cfg, batch_size=1, seq=48, iters=2)
    avg_max = np.mean([s[2] for s in stats])
    assert avg_max <= 1e-3, f"avg max logit err {avg_max}"


# ---------------------------------------------------------------------------
# bf16 parity at the reference's mixed-precision tolerance
# (getting_started.md:152-155: fp32 <=0.01, bf16/fp16 <=0.1)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("family,builder", [
    ("llama2", tiny_hf_llama),
    ("mistral", tiny_hf_mistral),
    ("falcon", tiny_hf_falcon),
])
def test_bf16_logit_parity(family, builder):
    hf = builder()
    cfg = config_from_hf(hf.config, family)
    cfg.training.params_dtype = "bfloat16"
    cfg.training.use_flash_attn = False
    stats = verify(hf, cfg, batch_size=1, seq=48, iters=2)
    avg_max = np.mean([s[2] for s in stats])
    assert avg_max <= 0.1, f"bf16 avg max logit err {avg_max}"


def test_codellama_realistic_shape_parity():
    """CodeLlama-flavored config at realistic proportions: GQA 8:1,
    rope_theta=1e6, linear rope scaling x2 (the 32K position-interpolation
    path, ref positional_embeddings.py:11, arguments.py:465-468)."""
    from transformers import LlamaConfig, LlamaForCausalLM

    hc = LlamaConfig(
        vocab_size=256, hidden_size=256, intermediate_size=688,
        num_hidden_layers=2, num_attention_heads=32, num_key_value_heads=4,
        max_position_embeddings=512, rms_norm_eps=1e-5, rope_theta=1e6,
        rope_scaling={"type": "linear", "factor": 2.0},
        tie_word_embeddings=False, attn_implementation="eager",
    )
    torch.manual_seed(3)
    hf = LlamaForCausalLM(hc)
    cfg = config_from_hf(hc, "codellama")
    assert cfg.model.rope_theta == 1e6
    assert cfg.model.rope_scaling_factor == 2.0
    assert cfg.model.num_attention_heads // cfg.model.num_attention_heads_kv == 8
    cfg.training.params_dtype = "float32"
    cfg.training.use_flash_attn = False
    stats = verify(hf, cfg, batch_size=1, seq=128, iters=2)
    avg_max = np.mean([s[2] for s in stats])
    assert avg_max <= 1e-3, f"avg max logit err {avg_max}"


# ---------------------------------------------------------------------------
# round trips: native -> HF == original, per family
# ---------------------------------------------------------------------------


def _round_trip(hf, family, state_fn_name, vocab=128):
    import weights_conversion.native_to_hf as n2h

    cfg = config_from_hf(hf.config, family)
    params = convert_hf_model(hf, cfg)
    state = getattr(n2h, state_fn_name)(params, cfg, vocab)
    hf2 = hf.__class__(n2h.hf_config_from_native(cfg, vocab))
    hf2.load_state_dict(
        {k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in state.items()}
    )
    tokens = torch.randint(0, vocab, (1, 32))
    with torch.no_grad():
        l1 = hf(tokens).logits.numpy()
        l2 = hf2(tokens).logits.numpy()
    np.testing.assert_allclose(l1, l2, atol=1e-5)


def test_mistral_round_trip():
    _round_trip(tiny_hf_mistral(), "mistral", "to_hf_llama_state")


# ---------------------------------------------------------------------------
# Mixtral (MoE) — beyond-reference family
# ---------------------------------------------------------------------------


def tiny_hf_mixtral():
    from transformers import MixtralConfig, MixtralForCausalLM

    mc = MixtralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=256, rms_norm_eps=1e-5, sliding_window=None,
        tie_word_embeddings=False, attn_implementation="eager",
    )
    torch.manual_seed(4)
    return MixtralForCausalLM(mc)


def test_mixtral_logit_parity():
    """HF Mixtral routes droplessly; with capacity >= tokens the capacity
    formulation is exactly dropless, so logits must match at the fp32 gate."""
    hf = tiny_hf_mixtral()
    cfg = config_from_hf(hf.config, "mixtral")
    assert cfg.model.num_experts == 4 and cfg.model.moe_router_topk == 2
    cfg.training.params_dtype = "float32"
    cfg.training.use_flash_attn = False
    cfg.model.moe_min_capacity = 4096  # dropless
    stats = verify(hf, cfg, batch_size=2, seq=48, iters=2)
    avg_max = np.mean([s[2] for s in stats])
    assert avg_max <= 1e-3, f"avg max logit err {avg_max}"


def test_mixtral_round_trip():
    _round_trip(tiny_hf_mixtral(), "mixtral", "to_hf_llama_state")


def test_falcon_round_trip():
    _round_trip(tiny_hf_falcon(), "falcon", "to_hf_falcon_state")


# ---------------------------------------------------------------------------
# Qwen2 (beyond-reference family): llama block + QKV-only bias, theta 1e6
# ---------------------------------------------------------------------------


def tiny_hf_qwen2():
    from transformers import Qwen2Config, Qwen2ForCausalLM

    qc = Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rms_norm_eps=1e-6, rope_theta=1e6,
        tie_word_embeddings=False, attn_implementation="eager",
    )
    torch.manual_seed(5)
    return Qwen2ForCausalLM(qc)


def test_qwen2_logit_parity():
    """The QKV bias must ride the same head-interleave + group-major fuse
    as the kernels — a mis-permuted bias shows up immediately at the fp32
    logit gate."""
    hf = tiny_hf_qwen2()
    cfg = config_from_hf(hf.config, "qwen2")
    assert cfg.model.add_qkv_bias and not cfg.model.use_bias
    assert cfg.model.rope_theta == 1e6
    cfg.training.params_dtype = "float32"
    cfg.training.use_flash_attn = False
    stats = verify(hf, cfg, batch_size=2, seq=48, iters=2)
    avg_max = np.mean([s[2] for s in stats])
    assert avg_max <= 1e-3, f"avg max logit err {avg_max}"


def test_qwen2_round_trip():
    _round_trip(tiny_hf_qwen2(), "qwen2", "to_hf_llama_state")


# ---------------------------------------------------------------------------
# dtype matrix + realistic scale (round-3 VERDICT item 4)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("family,builder", [
    ("llama2", tiny_hf_llama),
    ("mistral", tiny_hf_mistral),
    ("falcon", tiny_hf_falcon),
    ("mixtral", tiny_hf_mixtral),
])
def test_fp16_logit_parity(family, builder):
    """float16 params_dtype across the families (fp16 was untested in any
    parity suite before round 3). fp16 keeps 10 mantissa bits (vs bf16's
    7), so the gate is tighter than the bf16 one."""
    hf = builder()
    cfg = config_from_hf(hf.config, family)
    cfg.training.params_dtype = "float16"
    cfg.training.use_flash_attn = False
    stats = verify(hf, cfg, batch_size=1, seq=48, iters=2)
    avg_max = np.mean([s[2] for s in stats])
    assert avg_max <= 0.05, f"fp16 avg max logit err {avg_max}"


def test_mixtral_bf16_logit_parity():
    hf = tiny_hf_mixtral()
    cfg = config_from_hf(hf.config, "mixtral")
    cfg.training.params_dtype = "bfloat16"
    cfg.training.use_flash_attn = False
    stats = verify(hf, cfg, batch_size=1, seq=48, iters=2)
    avg_max = np.mean([s[2] for s in stats])
    assert avg_max <= 0.1, f"bf16 avg max logit err {avg_max}"


def _hf_llama_1b():
    """~1.05B-param Llama/CodeLlama-shaped model: h2048 x L24, 32 heads,
    GQA 8:1, SwiGLU ffn 5504, rope theta 1e6 + linear scaling x2 — the
    realistic-scale synthetic stand-in for the reference's flagship
    real-Llama-2-7B gate (test_llama_weights.py:91-118; real weights are
    impossible with zero egress)."""
    from transformers import LlamaConfig, LlamaForCausalLM

    hc = LlamaConfig(
        vocab_size=8192, hidden_size=2048, intermediate_size=5504,
        num_hidden_layers=24, num_attention_heads=32, num_key_value_heads=4,
        max_position_embeddings=1024, rms_norm_eps=1e-5, rope_theta=1e6,
        rope_scaling={"type": "linear", "factor": 2.0},
        tie_word_embeddings=False, attn_implementation="eager",
    )
    torch.manual_seed(7)
    return LlamaForCausalLM(hc)


@pytest.mark.slow
@pytest.mark.parametrize("dtype,gate,ref_abs_gate", [
    # gate: per-token avg-MAX error (test_llama_weights.py:117 metric);
    # ref_abs_gate: the reference's PUBLISHED contract — "average absolute
    # error smaller than 0.01 when using 32-bit precision and 0.1 when
    # using 16-bit precision" (getting_started.md:154) — asserted
    # alongside so the reduced-precision gates are anchored to the ref
    # contract, not to what this implementation happens to produce
    # (round-3 VERDICT weak item 5)
    ("float32", 1e-3, 0.01),
    ("bfloat16", 0.5, 0.1),   # 24 layers of bf16 rounding, realistic width
    ("float16", 0.25, 0.1),
])
def test_llama_1b_realistic_parity(dtype, gate, ref_abs_gate):
    hf = _hf_llama_1b()
    n_params = sum(p.numel() for p in hf.parameters())
    assert n_params > 1.0e9, n_params
    cfg = config_from_hf(hf.config, "codellama")
    assert cfg.model.rope_theta == 1e6
    assert cfg.model.rope_scaling_factor == 2.0
    cfg.training.params_dtype = dtype
    cfg.training.use_flash_attn = False
    stats = verify(hf, cfg, batch_size=1, seq=256, iters=1)
    avg_max = np.mean([s[2] for s in stats])
    assert avg_max <= gate, f"{dtype} avg max logit err {avg_max}"
    avg_abs = np.mean([s[1] for s in stats])
    assert avg_abs <= ref_abs_gate, (
        f"{dtype} avg abs logit err {avg_abs} exceeds the reference "
        f"contract {ref_abs_gate} (getting_started.md:154)")


def tiny_hf_llama3(vocab=160):
    """Llama-3.1-shaped: GQA + theta 5e5 + the "llama3" rope remap active
    (orig_max 64 < max_pos 128, factor 4 — the remap actually changes
    frequencies at these dims)."""
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=vocab, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=500_000.0,
        rope_scaling={"rope_type": "llama3", "factor": 4.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 64},
        tie_word_embeddings=False, attn_implementation="eager",
    )
    torch.manual_seed(3)
    return LlamaForCausalLM(cfg)


def test_llama3_logit_parity_rope_remap():
    """The llama3 remap at logit level against HF's own forward — proves
    the converted model reproduces Llama-3.1 numerics, not just configs."""
    hf = tiny_hf_llama3()
    cfg = config_from_hf(hf.config, "llama3")
    assert cfg.model.rope_scaling_type == "llama3"
    cfg.training.params_dtype = "float32"
    cfg.training.use_flash_attn = False
    stats = verify(hf, cfg, batch_size=2, seq=96, iters=2)
    avg_max = np.mean([s[2] for s in stats])
    assert avg_max <= 1e-3, f"avg max logit err {avg_max}"


def test_llama3_round_trip():
    from weights_conversion import native_to_hf as n2h

    hf = tiny_hf_llama3()
    cfg = config_from_hf(hf.config, "llama3")
    cfg.training.params_dtype = "float32"
    params = convert_hf_model(hf, cfg)
    back = n2h.hf_config_from_native(cfg, vocab_size=hf.config.vocab_size)
    assert back.rope_scaling["rope_type"] == "llama3"
    assert back.rope_scaling["original_max_position_embeddings"] == 64
    assert back.rope_theta == 500_000.0


def test_llama32_tied_embeddings_parity():
    """Llama-3.2 small models tie embeddings; the tying must pass through
    conversion (not silently untie) and reproduce HF logits."""
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg_hf = LlamaConfig(
        vocab_size=160, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=500_000.0,
        tie_word_embeddings=True, attn_implementation="eager",
    )
    torch.manual_seed(4)
    hf = LlamaForCausalLM(cfg_hf)
    cfg = config_from_hf(hf.config, "llama3")
    assert cfg.model.tie_embed_logits
    cfg.training.params_dtype = "float32"
    cfg.training.use_flash_attn = False
    stats = verify(hf, cfg, batch_size=2, seq=48, iters=2)
    avg_max = np.mean([s[2] for s in stats])
    assert avg_max <= 1e-3, f"avg max logit err {avg_max}"
