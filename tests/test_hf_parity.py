"""HF logit parity through weight conversion (the reference's flagship
test_llama_weights.py lifecycle, with random-init tiny HF models instead of
real weights — zero-egress friendly)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from megatron_llm_tpu.models import make_config, model_forward
from verify_correctness import verify
from weights_conversion.hf_to_native import (
    config_from_hf,
    convert_hf_model,
)


def tiny_hf_llama(nkv=2, vocab=128):
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=vocab, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=nkv,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False, attn_implementation="eager",
    )
    torch.manual_seed(0)
    return LlamaForCausalLM(cfg)


def tiny_hf_mistral():
    from transformers import MistralConfig, MistralForCausalLM

    cfg = MistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rms_norm_eps=1e-5, sliding_window=32,
        tie_word_embeddings=False, attn_implementation="eager",
    )
    torch.manual_seed(1)
    return MistralForCausalLM(cfg)


@pytest.mark.parametrize("nkv", [4, 2])
def test_llama_logit_parity(nkv):
    hf = tiny_hf_llama(nkv=nkv)
    cfg = config_from_hf(hf.config, "llama2")
    cfg.training.params_dtype = "float32"
    cfg.training.use_flash_attn = False
    stats = verify(hf, cfg, batch_size=2, seq=48, iters=2)
    avg_max = np.mean([s[2] for s in stats])
    # reference gate: avg per-token max abs err <= 1e-3 (test_llama_weights.py:117)
    assert avg_max <= 1e-3, f"avg max logit err {avg_max}"


def test_mistral_logit_parity_sliding_window():
    hf = tiny_hf_mistral()
    cfg = config_from_hf(hf.config, "mistral")
    cfg.training.params_dtype = "float32"
    cfg.training.use_flash_attn = False
    assert cfg.model.sliding_window_size == 32
    # seq > window so the window actually matters
    stats = verify(hf, cfg, batch_size=1, seq=96, iters=2)
    avg_max = np.mean([s[2] for s in stats])
    assert avg_max <= 1e-3, f"avg max logit err {avg_max}"


def test_hf_round_trip():
    """native -> HF -> logits identical to the original HF model."""
    from weights_conversion.native_to_hf import (
        hf_config_from_native,
        to_hf_llama_state,
    )

    hf = tiny_hf_llama(nkv=2)
    cfg = config_from_hf(hf.config, "llama2")
    params = convert_hf_model(hf, cfg)
    state = to_hf_llama_state(params, cfg, vocab_size=128)

    from transformers import LlamaForCausalLM

    hf2 = LlamaForCausalLM(hf_config_from_native(cfg, 128))
    hf2.load_state_dict(
        {k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in state.items()}
    )
    tokens = torch.randint(0, 128, (1, 32))
    with torch.no_grad():
        l1 = hf(tokens).logits.numpy()
        l2 = hf2(tokens).logits.numpy()
    np.testing.assert_allclose(l1, l2, atol=1e-5)


def test_falcon_logit_parity():
    from transformers import FalconConfig, FalconForCausalLM

    fc = FalconConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_kv_heads=2, new_decoder_architecture=True,
        parallel_attn=True, bias=False, alibi=False,
        max_position_embeddings=128, attn_implementation="eager",
    )
    torch.manual_seed(2)
    hf = FalconForCausalLM(fc)
    cfg = config_from_hf(fc, "falcon")
    cfg.training.params_dtype = "float32"
    cfg.training.use_flash_attn = False
    stats = verify(hf, cfg, batch_size=1, seq=48, iters=2)
    avg_max = np.mean([s[2] for s in stats])
    assert avg_max <= 1e-3, f"avg max logit err {avg_max}"
