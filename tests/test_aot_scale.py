"""AOT compile-for-topology path (tools/aot_scale_check.py).

Validates on small shapes what the tool proves at 7B-70B scale: the full
jitted train step lowers and compiles for a VIRTUAL TPU topology from a CPU
host, with abstract (never materialized) params/optimizer state, the Pallas
flash kernel in the compiled program (kernel dispatch keys on the mesh
target platform, core/parallel_state.target_platform), and the 1F1B
schedule's nested shard_map composing with the manual pp axis.
"""

from __future__ import annotations

import functools
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

topologies = pytest.importorskip("jax.experimental.topologies")

# get_topology_desc initializes the TPU PJRT plugin, which can HANG
# INDEFINITELY (not raise) when a libtpu tunnel env is present but wedged —
# that hang turned whole-suite runs into multi-hundred-second stalls (and a
# hung in-process init thread would poison jax's plugin lock through exit).
# So the init is probed in a SUBPROCESS with a hard timeout (the bench.py
# probe_backend pattern); only a healthy probe lets the real in-process
# init run.  The verdict is cached per topology: one bounded probe per
# process, shared by every test using that topology.
_TOPO_CACHE: dict = {}
_TOPO_TIMEOUT_S = 20.0


def _probe_topology(name: str) -> str | None:
    """None if the topology initializes cleanly in a subprocess; else the
    reason to skip."""
    code = ("import jax.experimental.topologies as t; "
            f"t.get_topology_desc({name!r}, 'tpu')")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=_TOPO_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        return (f"PJRT topology init exceeded {_TOPO_TIMEOUT_S:.0f}s "
                "(wedged libtpu tunnel?)")
    if r.returncode != 0:
        tail = (r.stderr or r.stdout).strip().splitlines()
        return tail[-1] if tail else f"probe exited {r.returncode}"
    return None


def _topo_devices(name):
    if name not in _TOPO_CACHE:
        reason = _probe_topology(name)
        if reason is None:
            try:
                topo = topologies.get_topology_desc(name, "tpu")
                _TOPO_CACHE[name] = ("ok", topo)
            except Exception as e:
                _TOPO_CACHE[name] = ("err", f"{type(e).__name__}: {e}")
        else:
            _TOPO_CACHE[name] = ("err", reason)
    status, val = _TOPO_CACHE[name]
    if status != "ok":
        pytest.skip(f"TPU topology unavailable: {val}")
    return list(np.array(val.devices).ravel())


def _lower_and_compile(cfg, mesh, gbs, seq, extra_batch=None):
    from megatron_llm_tpu.core.parallel_state import global_mesh
    from megatron_llm_tpu.models import init_model_params
    from megatron_llm_tpu.optimizer.optimizer import get_optimizer
    from megatron_llm_tpu.training_step import make_jitted_train_step

    with global_mesh(mesh):
        params_abs = jax.eval_shape(
            functools.partial(init_model_params, cfg), jax.random.PRNGKey(0))
        opt = get_optimizer(cfg, params_abs)
        opt_abs = jax.eval_shape(opt.init, params_abs)
        step, _o, _sh = make_jitted_train_step(
            cfg, mesh, params_abs, optimizer=opt, opt_state=opt_abs)
        batch = {
            "tokens": jax.ShapeDtypeStruct((gbs, seq), jnp.int32),
            "labels": jax.ShapeDtypeStruct((gbs, seq), jnp.int32),
            "loss_mask": jax.ShapeDtypeStruct((gbs, seq), jnp.float32),
            **(extra_batch or {}),
        }
        lowered = step.lower(params_abs, opt_abs, batch,
                             jax.ShapeDtypeStruct((), jnp.int32))
        return lowered, lowered.compile()


def test_aot_dense_tp8_includes_flash_kernel():
    from megatron_llm_tpu.core.parallel_state import build_mesh, target_platform, global_mesh
    from megatron_llm_tpu.models import make_config

    devices = _topo_devices("v5e:2x4")
    mesh = build_mesh(tensor_model_parallel_size=8, devices=devices)
    with global_mesh(mesh):
        assert target_platform() == "tpu"  # CPU host, TPU compile target
    cfg = make_config(
        "llama2", num_layers=2, hidden_size=512, num_attention_heads=8,
        num_attention_heads_kv=8, vocab_size=2048, seq_length=256,
        max_position_embeddings=256, params_dtype="bfloat16",
        tensor_model_parallel_size=8, sequence_parallel=True,
        use_distributed_optimizer=True, micro_batch_size=1,
        global_batch_size=2, train_iters=10)
    cfg.parallel.num_micro_batches = 2
    lowered, compiled = _lower_and_compile(cfg, mesh, 2, 256)
    hlo = lowered.as_text()
    assert "tpu_custom_call" in hlo or "mosaic" in hlo.lower(), (
        "AOT lowering must contain the Pallas flash kernel")
    m = compiled.memory_analysis()
    assert m.argument_size_in_bytes > 0


def test_aot_1f1b_vpp_nested_shard_map_composes():
    """Regression: _flash_sharded inside the pipeline's manual (pp) context
    must bind the context abstract mesh (ops/attention.py)."""
    from megatron_llm_tpu.core.parallel_state import build_mesh
    from megatron_llm_tpu.models import make_config

    devices = _topo_devices("v5p:2x4x4")
    mesh = build_mesh(tensor_model_parallel_size=8,
                      pipeline_model_parallel_size=4, devices=devices)
    cfg = make_config(
        "falcon", num_layers=8, hidden_size=512, num_attention_heads=8,
        num_attention_heads_kv=8, ffn_hidden_size=2048, vocab_size=2048,
        seq_length=256, max_position_embeddings=256,
        params_dtype="bfloat16",
        tensor_model_parallel_size=8, pipeline_model_parallel_size=4,
        sequence_parallel=True, use_distributed_optimizer=True,
        micro_batch_size=1, global_batch_size=8, train_iters=10)
    cfg.parallel.num_micro_batches = 8
    cfg.parallel.pipeline_schedule = "1f1b"
    cfg.parallel.virtual_pipeline_model_parallel_size = 2
    cfg.parallel.recompute_granularity = "full"
    cfg.finalize()
    _lowered, compiled = _lower_and_compile(cfg, mesh, 8, 256)
    assert compiled.memory_analysis().argument_size_in_bytes > 0


def test_aot_striped_zigzag_ring_compiles():
    """The striped (zigzag) flash ring composes with the FULL jitted train
    step for a TPU target: cp2 + cp_zigzag + a token_idx batch must lower
    the half-chunk Mosaic kernels (the CPU dryrun can only exercise the
    jnp fallback — dispatch is TPU-target-only)."""
    from megatron_llm_tpu.core.parallel_state import build_mesh
    from megatron_llm_tpu.models import make_config
    from megatron_llm_tpu.parallel.ring import zigzag_permutation

    devices = _topo_devices("v5e:2x4")
    mesh = build_mesh(tensor_model_parallel_size=2, context_parallel_size=2,
                      data_parallel_size=2, devices=devices)
    cfg = make_config(
        "llama2", num_layers=2, hidden_size=512, num_attention_heads=8,
        num_attention_heads_kv=8, ffn_hidden_size=1024, vocab_size=4096,
        seq_length=1024, max_position_embeddings=1024,
        params_dtype="bfloat16",
        tensor_model_parallel_size=2, context_parallel_size=2,
        sequence_parallel=True, use_distributed_optimizer=True,
        micro_batch_size=1, global_batch_size=2, train_iters=10)
    cfg.parallel.data_parallel_size = 2
    cfg.parallel.num_micro_batches = 1
    cfg.parallel.cp_zigzag = True
    cfg.finalize()
    gbs, s = 2, 1024
    lowered, compiled = _lower_and_compile(cfg, mesh, gbs, s, extra_batch={
        "position_ids": jax.ShapeDtypeStruct((gbs, s), jnp.int32),
        "token_idx": jax.ShapeDtypeStruct(
            zigzag_permutation(s, 2).shape, jnp.int32),
    })
    assert lowered.as_text().count("tpu_custom_call") > 0, (
        "striped ring must lower Mosaic kernels, not the jnp fallback")
    assert compiled.memory_analysis().argument_size_in_bytes > 0


def test_aot_pp_dp_tp_flash_no_partitioner_crash():
    """Round-5 regression for the round-4 north-star blocker: the
    dp2 x pp2 x tp2 combo (1F1B + ZeRO-1 + full remat + nested-manual
    flash) CHECK-crashed XLA's scatter partitioner via the embedding-grad
    scatter-add inside the tick loop (spmd_partitioner_util.cc:506). With
    the matmul-backward embedding (language_model._take_rows_matmul_bwd)
    it must compile WITH the flash kernel in the HLO — the same structure
    tools/aot_scale_check.py certifies at tp8 x pp8 x dp4 / 70B."""
    from megatron_llm_tpu.core.parallel_state import build_mesh
    from megatron_llm_tpu.models import make_config

    devices = _topo_devices("v5e:2x4")
    mesh = build_mesh(tensor_model_parallel_size=2,
                      pipeline_model_parallel_size=2,
                      data_parallel_size=2, devices=devices)
    cfg = make_config(
        "llama2", num_layers=2, hidden_size=512, num_attention_heads=8,
        num_attention_heads_kv=8, ffn_hidden_size=1024, vocab_size=4096,
        seq_length=512, max_position_embeddings=512,
        params_dtype="bfloat16",
        tensor_model_parallel_size=2, pipeline_model_parallel_size=2,
        sequence_parallel=True, use_distributed_optimizer=True,
        micro_batch_size=1, global_batch_size=8, train_iters=10)
    cfg.parallel.data_parallel_size = 2
    cfg.parallel.num_micro_batches = 4
    cfg.parallel.pipeline_schedule = "1f1b"
    cfg.parallel.recompute_granularity = "full"
    cfg.finalize()
    lowered, compiled = _lower_and_compile(cfg, mesh, 8, 512)
    assert lowered.as_text().count("tpu_custom_call") > 0, (
        "flash must dispatch at the pp x dp x tp layout, not fall back")
    assert compiled.memory_analysis().argument_size_in_bytes > 0
