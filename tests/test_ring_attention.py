"""Ring-attention (context parallelism) correctness on the 8-device CPU mesh.

The reference has no CP (SURVEY §2.1); correctness target is therefore the
single-device exact attention (ops/attention.xla_attention) and the
single-device full model, which the cp-sharded versions must reproduce.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.core.parallel_state import build_mesh, global_mesh
from megatron_llm_tpu.models import init_model_params, make_config, model_forward
from megatron_llm_tpu.ops.attention import make_attention_bias, xla_attention
from megatron_llm_tpu.parallel.ring import (
    apply_zigzag,
    ring_attention,
    zigzag_permutation,
)
from megatron_llm_tpu.parallel.tp import make_sp_constraint, param_shardings


def _qkv(key, b=2, s=64, n=4, nkv=2, d=16, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, n, d), dtype)
    k = jax.random.normal(kk, (b, s, nkv, d), dtype)
    v = jax.random.normal(kv, (b, s, nkv, d), dtype)
    return q, k, v


def _reference(q, k, v, *, sliding_window=None, segment_ids=None, token_idx=None):
    bias = make_attention_bias(
        q.shape[1], k.shape[1], causal=True, sliding_window=sliding_window,
        segment_ids_q=segment_ids, segment_ids_kv=segment_ids,
        token_idx=token_idx,
    )
    return xla_attention(q, k, v, bias=bias)


@pytest.mark.parametrize("cp,dp", [(4, 1), (2, 2), (8, 1)])
def test_ring_matches_exact(eight_devices, cp, dp):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    ref = _reference(q, k, v)
    mesh = build_mesh(context_parallel_size=cp, data_parallel_size=dp,
                      devices=eight_devices[: cp * dp])
    with global_mesh(mesh):
        out = jax.jit(lambda a, b, c: ring_attention(a, b, c))(q, k, v)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               atol=1e-5, rtol=1e-5)


def test_ring_sliding_window(eight_devices):
    q, k, v = _qkv(jax.random.PRNGKey(1))
    ref = _reference(q, k, v, sliding_window=17)
    mesh = build_mesh(context_parallel_size=4, devices=eight_devices[:4])
    with global_mesh(mesh):
        out = ring_attention(q, k, v, sliding_window=17)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               atol=1e-5, rtol=1e-5)


def test_ring_segment_ids(eight_devices):
    q, k, v = _qkv(jax.random.PRNGKey(2))
    # two packed documents per row, different split points per row
    seg = jnp.stack([
        jnp.concatenate([jnp.zeros(20, jnp.int32), jnp.ones(44, jnp.int32)]),
        jnp.concatenate([jnp.zeros(40, jnp.int32), jnp.ones(24, jnp.int32)]),
    ])
    ref = _reference(q, k, v, segment_ids=seg)
    mesh = build_mesh(context_parallel_size=4, devices=eight_devices[:4])
    with global_mesh(mesh):
        out = ring_attention(q, k, v, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               atol=1e-5, rtol=1e-5)


def test_ring_gqa_heads_over_tp(eight_devices):
    """cp=2 x tp=2: heads sharded over tp inside the same shard_map."""
    q, k, v = _qkv(jax.random.PRNGKey(3), n=8, nkv=4)
    ref = _reference(q, k, v)
    mesh = build_mesh(context_parallel_size=2, tensor_model_parallel_size=2,
                      data_parallel_size=2, devices=eight_devices)
    with global_mesh(mesh):
        out = ring_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               atol=1e-5, rtol=1e-5)


def test_zigzag_permutation_balanced():
    cp, s = 4, 64
    perm = zigzag_permutation(s, cp)
    assert sorted(perm.tolist()) == list(range(s))
    # causal work per rank (number of unmasked pairs) is perfectly balanced
    chunks = perm.reshape(cp, s // cp)
    work = [
        int(np.sum(c[:, None] >= np.arange(s)[None, :])) for c in chunks
    ]
    assert max(work) - min(work) <= s // cp, work


def test_ring_zigzag_matches_exact(eight_devices):
    """Zigzag-permuted ring attention == exact attention permuted."""
    q, k, v = _qkv(jax.random.PRNGKey(4))
    ref = _reference(q, k, v)
    cp = 4
    perm = zigzag_permutation(q.shape[1], cp)
    token_idx = jnp.asarray(perm, jnp.int32)
    qp, kp, vp = q[:, perm], k[:, perm], v[:, perm]
    mesh = build_mesh(context_parallel_size=cp, devices=eight_devices[:cp])
    with global_mesh(mesh):
        out = ring_attention(qp, kp, vp, token_idx=token_idx)
    np.testing.assert_allclose(
        np.asarray(ref[:, perm]), np.asarray(out), atol=1e-5, rtol=1e-5
    )


def test_ring_gradients_match(eight_devices):
    """Autodiff through the ring (ppermute transpose) == exact-attention grads."""
    q, k, v = _qkv(jax.random.PRNGKey(5), b=1, s=32)

    def loss_ref(q_, k_, v_):
        return (_reference(q_, k_, v_) ** 2).sum()

    gref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    mesh = build_mesh(context_parallel_size=4, devices=eight_devices[:4])
    with global_mesh(mesh):
        def loss_ring(q_, k_, v_):
            return (ring_attention(q_, k_, v_) ** 2).sum()

        gring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gref, gring):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def _tiny_cfg(cp=1, tp=1, sp=False):
    cfg = make_config(
        "llama2",
        num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, vocab_size=256, seq_length=32,
        max_position_embeddings=64, params_dtype="float32",
        use_flash_attn=False,
        tensor_model_parallel_size=tp, sequence_parallel=sp,
        context_parallel_size=cp,
    )
    return cfg


def test_model_forward_cp_matches_single(eight_devices):
    """Full model logits with cp=4 == single-device logits."""
    cfg1 = _tiny_cfg()
    params = init_model_params(cfg1, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 256)
    ref, _ = model_forward(cfg1, params, tokens)

    cfgN = _tiny_cfg(cp=4, tp=2)
    mesh = build_mesh(context_parallel_size=4, tensor_model_parallel_size=2,
                      devices=eight_devices)
    with global_mesh(mesh):
        sharded = jax.device_put(params, param_shardings(mesh, params))
        sp_c = make_sp_constraint(cfgN)

        @jax.jit
        def fwd(p, t):
            out, _ = model_forward(cfgN, p, t, sp_constraint=sp_c)
            return out

        got = fwd(sharded, tokens)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               atol=2e-4, rtol=2e-4)


def test_train_step_cp_matches_single(eight_devices):
    """One train step on cp=2 x dp=2 x tp=2 == single-device numerics."""
    from megatron_llm_tpu.training_step import make_jitted_train_step

    tok = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0, 256)
    batch = {
        "tokens": np.asarray(tok[:, :-1]),
        "labels": np.asarray(tok[:, 1:]),
        "loss_mask": np.ones((4, 32), np.float32),
    }
    results = {}
    for name, (cp, tp, dp) in {
        "single": (1, 1, 1), "cp2tp2dp2": (2, 2, 2),
    }.items():
        cfg = _tiny_cfg(cp=cp, tp=tp)
        cfg.parallel.data_parallel_size = dp
        cfg.training.global_batch_size = 4
        cfg.training.micro_batch_size = 4 // dp
        cfg.finalize()
        mesh = build_mesh(
            context_parallel_size=cp, tensor_model_parallel_size=tp,
            data_parallel_size=dp,
            devices=eight_devices[: cp * tp * dp],
        )
        params = init_model_params(cfg, jax.random.PRNGKey(0))
        with global_mesh(mesh):
            step, _o, sh = make_jitted_train_step(cfg, mesh, params)
            p = jax.device_put(params, sh["params"])
            o = jax.device_put(sh["opt_state_value"], sh["opt_state"])
            b = sh["place_batch"](batch)
            p, o, metrics = step(p, o, b, jnp.zeros((), jnp.int32))
            results[name] = (
                float(metrics["lm loss"]),
                np.asarray(jax.tree_util.tree_leaves(p)[0]),
            )
    assert abs(results["single"][0] - results["cp2tp2dp2"][0]) < 1e-5
    np.testing.assert_allclose(results["single"][1], results["cp2tp2dp2"][1],
                               atol=1e-4, rtol=1e-4)


def test_train_step_pp_cp_matches_single(eight_devices):
    """pp=2 x cp=2 x tp=2 (cp manual inside the pipeline body) == single."""
    from megatron_llm_tpu.training_step import make_jitted_train_step

    tok = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0, 256)
    batch = {
        "tokens": np.asarray(tok[:, :-1]),
        "labels": np.asarray(tok[:, 1:]),
        "loss_mask": np.ones((4, 32), np.float32),
    }
    results = {}
    for name, (pp, cp, tp) in {
        "single": (1, 1, 1), "pp2cp2tp2": (2, 2, 2),
    }.items():
        cfg = _tiny_cfg(cp=cp, tp=tp)
        cfg.parallel.pipeline_model_parallel_size = pp
        cfg.parallel.data_parallel_size = 1
        cfg.training.global_batch_size = 4
        cfg.training.micro_batch_size = 2
        cfg.parallel.num_micro_batches = 2
        cfg.finalize()
        mesh = build_mesh(
            pipeline_model_parallel_size=pp, context_parallel_size=cp,
            tensor_model_parallel_size=tp, data_parallel_size=1,
            devices=eight_devices[: pp * cp * tp],
        )
        params = init_model_params(cfg, jax.random.PRNGKey(0))
        with global_mesh(mesh):
            step, _o, sh = make_jitted_train_step(cfg, mesh, params)
            p = jax.device_put(params, sh["params"])
            o = jax.device_put(sh["opt_state_value"], sh["opt_state"])
            b = sh["place_batch"](batch)
            p, o, metrics = step(p, o, b, jnp.zeros((), jnp.int32))
            results[name] = (
                float(metrics["lm loss"]),
                np.asarray(jax.tree_util.tree_leaves(p)[0]),
            )
    assert abs(results["single"][0] - results["pp2cp2tp2"][0]) < 2e-4
    # Adam amplifies fp32-noise-level grad differences to O(lr) param
    # differences on near-zero-grad entries; 1e-3 ~ 3*lr is the meaningful
    # bound here (loss equality above is the tight check).
    np.testing.assert_allclose(results["single"][1], results["pp2cp2tp2"][1],
                               atol=1e-3, rtol=1e-3)


def test_pipeline_zigzag_token_idx(eight_devices):
    """pp=2 x cp=2 with a zigzag batch: loss == pp=1 cp=1 natural-order loss."""
    from megatron_llm_tpu.models.language_model import loss_from_batch
    from megatron_llm_tpu.parallel.pipeline import pipeline_loss_fn

    cfg1 = _tiny_cfg()
    params = init_model_params(cfg1, jax.random.PRNGKey(0))
    tok = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0, 256)
    batch = {
        "tokens": np.asarray(tok[:, :-1]),
        "labels": np.asarray(tok[:, 1:]),
        "loss_mask": np.ones((4, 32), np.float32),
    }
    ref_loss, _ = loss_from_batch(cfg1, params, batch)

    cfgN = _tiny_cfg(cp=2)
    cfgN.parallel.pipeline_model_parallel_size = 2
    cfgN.parallel.data_parallel_size = 1
    cfgN.parallel.num_micro_batches = 2
    cfgN.finalize()
    zz = apply_zigzag(batch, cp=2)
    mesh = build_mesh(pipeline_model_parallel_size=2, context_parallel_size=2,
                      data_parallel_size=1, devices=eight_devices[:4])
    with global_mesh(mesh):
        loss, _ = jax.jit(
            lambda p, b: pipeline_loss_fn(cfgN, mesh, p, b)
        )(params, {k: jnp.asarray(v) for k, v in zz.items()})
    assert abs(float(ref_loss) - float(loss)) < 1e-4, (ref_loss, loss)


def test_zigzag_batch_transform():
    b, s, cp = 2, 32, 4
    batch = {
        "tokens": np.arange(b * s).reshape(b, s) % 97,
        "labels": np.arange(b * s).reshape(b, s) % 89,
        "loss_mask": np.ones((b, s), np.float32),
    }
    out = apply_zigzag(batch, cp)
    perm = zigzag_permutation(s, cp)
    assert np.array_equal(out["token_idx"], perm)
    assert np.array_equal(out["tokens"], batch["tokens"][:, perm])
    assert np.array_equal(out["position_ids"][0], perm)


def test_ring_q_row_blocking_parity(eight_devices, monkeypatch):
    """The long-seq row-blocked online softmax (ring._Q_BLOCK_THRESHOLD)
    matches the unblocked path exactly — forced on at small seq by
    shrinking the threshold, against the same exact reference."""
    from megatron_llm_tpu.parallel import ring as ring_mod

    q, k, v = _qkv(jax.random.PRNGKey(7))
    ref = _reference(q, k, v)
    mesh = build_mesh(context_parallel_size=2, devices=eight_devices[:2])
    monkeypatch.setattr(ring_mod, "_Q_BLOCK_THRESHOLD", 8)
    monkeypatch.setattr(ring_mod, "_Q_BLOCK_ROWS", 8)
    with global_mesh(mesh):
        out = ring_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               atol=1e-5, rtol=1e-5)
    # and with segments + zigzag token_idx (the full masking surface)
    seg = jnp.stack([
        jnp.concatenate([jnp.zeros(24, jnp.int32), jnp.ones(40, jnp.int32)]),
        jnp.concatenate([jnp.zeros(50, jnp.int32), jnp.ones(14, jnp.int32)]),
    ])
    ref_seg = _reference(q, k, v, segment_ids=seg)
    perm = zigzag_permutation(64, 2)
    tok_idx = jnp.asarray(perm, jnp.int32)
    qp, kp, vp = q[:, perm], k[:, perm], v[:, perm]
    with global_mesh(mesh):
        outp = ring_attention(qp, kp, vp, segment_ids=seg[:, perm],
                              token_idx=tok_idx)
    inv = np.argsort(perm)
    np.testing.assert_allclose(np.asarray(ref_seg),
                               np.asarray(outp)[:, inv],
                               atol=1e-5, rtol=1e-5)


def test_choose_q_block_never_degenerates():
    """Q-row block selection (round-3 advisor finding): non-smooth local
    seq lengths must never fall toward blk=1 (up to sq sequential scan
    iterations per ring step); they fall UP to a bounded over-budget
    divisor or raise with guidance."""
    from megatron_llm_tpu.parallel.ring import (
        _Q_BLOCK_MIN, _Q_BLOCK_OVER, _Q_BLOCK_ROWS, _Q_BLOCK_THRESHOLD,
        _choose_q_block,
    )

    # short seqs: one full block
    assert _choose_q_block(4096) == 4096
    assert _choose_q_block(17) == 17
    # smooth seqs: largest divisor within budget
    assert _choose_q_block(16384) == 2048
    assert _choose_q_block(5120) == 1280
    # 2 * prime: in-budget divisors are only {1, 2} -> falls UP to p=4801
    # (within the 4x-budget ceiling)
    assert _choose_q_block(2 * 4801) == 4801
    # prime <= 4x budget: the seq itself is the only usable divisor
    assert _choose_q_block(8191) == 8191
    # prime with no divisor at all in [min, 4x budget] -> clear error
    with pytest.raises(ValueError, match="row-blocked"):
        _choose_q_block(16411)
    # every accepted block divides exactly and respects the bounds
    for sq in (8192, 12288, 5120, 6144, 9602, 32768):
        blk = _choose_q_block(sq)
        assert sq % blk == 0
        if sq > _Q_BLOCK_THRESHOLD:
            assert _Q_BLOCK_MIN <= blk <= _Q_BLOCK_OVER
