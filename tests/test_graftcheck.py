"""tools/graftcheck — the AST invariant analyzer (tier-1 CI gate).

Four layers:

(a) per-rule fixtures — for every rule: a positive (the violation is
    found), a negative (the compliant twin is clean), a suppressed
    variant (``# graftcheck: noqa[rule]`` silences exactly that
    finding) and a baselined variant (a baseline entry absorbs it);
(b) the historical-bug fixtures — each new analyzer reproduces the real
    regression it exists to prevent (id-keyed cached_jit from PR 1, the
    direct shard_map import that cost 8 tests, a device-syncing
    instrument, unguarded shared state, pinned-key reuse);
(c) the CLI contract — JSON schema, exit codes 0/1/2 (the tpu_watch
    predicate distinguishes analyzer crashes from findings), and the
    tools/linter.py shim's legacy surface;
(d) the full-repo sweep — zero non-baselined findings on this tree,
    every baseline entry explained, no stale entries, under the 30 s
    budget.  THIS is the gate: a PR that introduces a violation fails
    here with the exact finding text.

Note every forbidden spelling in the fixtures below is composed from
string fragments: the legacy lexical sweep (tools/linter.py
SHARD_MAP_RE, still pinned by older tests) scans raw test-file lines.
"""

from __future__ import annotations

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.graftcheck import core  # noqa: E402
from tools.graftcheck.rules import (  # noqa: E402
    ALL_RULES,
    DEFAULT_RULES,
    PROJECT_RULES,
    RULES_BY_ID,
)

_SM = "shard" + "_map"  # keep the spelling out of raw source lines
_DG = "device" + "_get"
_BUR = "block_until" + "_ready"


def findings_for(src: str, path: str = "fixture.py",
                 rules=None):
    fs = core.check_file(path, rules or ALL_RULES, source=src)
    return fs


def rules_hit(src: str, path: str = "fixture.py"):
    return sorted({f.rule for f in findings_for(src, path)})


# ---------------------------------------------------------------------------
# (a) per-rule positive / negative / suppressed / baselined
# ---------------------------------------------------------------------------

# rule id -> (positive source, negative twin).  The positive must yield
# at least one finding of that rule; the negative must yield none.
FIXTURES = {
    "todo-owner": (
        "x = 1  # TODO fix this\n",
        'x = 1  # TODO(mika) fix this\ns = "a TODO in a string is data"\n',
    ),
    "obs-no-sync": (
        f"import jax\nx = jax.{_DG}(y)\n",
        f'"""Docstring may say {_DG} and {_BUR} freely now."""\n'
        f"# prose comment about {_DG} is fine too\nx = 1\n",
    ),
    "no-direct-shard-map": (
        f"from jax import {_SM}\n",
        f'msg = "jax.{_SM} is unavailable on 0.4.37"\n'
        f"from megatron_llm_tpu.parallel.compat import {_SM}\n",
    ),
    "sync-in-jit": (
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return float(x)\n",
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return x * 2\n"
        "def host(x):\n"
        "    return float(x)\n",
    ),
    "lock-discipline": (
        "import threading\n"
        "class Pool:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._free = []  # guarded by _lock\n"
        "    def take(self):\n"
        "        return self._free.pop()\n",
        "import threading\n"
        "class Pool:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._free = []  # guarded by _lock\n"
        "    def take(self):\n"
        "        with self._lock:\n"
        "            return self._free.pop()\n",
    ),
    "rng-key-reuse": (
        "import jax\n"
        "def sample(key):\n"
        "    a = jax.random.normal(key)\n"
        "    b = jax.random.uniform(key)\n"
        "    return a + b\n",
        "import jax\n"
        "def sample(key):\n"
        "    key, sub = jax.random.split(key)\n"
        "    a = jax.random.normal(sub)\n"
        "    key, sub = jax.random.split(key)\n"
        "    return a + jax.random.uniform(sub)\n",
    ),
    "recompile-hazard": (
        "import jax\n"
        "def make(cfg, build, cache):\n"
        "    k = (id(cfg), 'tick')\n"
        "    if k not in cache:\n"
        "        cache[k] = jax.jit(build())\n"
        "    return cache[k]\n",
        "import jax\n"
        "def make(cfg, build, cache, fingerprint):\n"
        "    k = (fingerprint(cfg), 'tick')\n"
        "    if k not in cache:\n"
        "        cache[k] = jax.jit(build())\n"
        "    return cache[k]\n",
    ),
    "span-device-attr": (
        # ISSUE 12: a jax array as a span/flight-event attr defers a
        # host sync to dump time — flagged whether passed directly or
        # through a name bound to a device-producing call
        "import jax.numpy as jnp\n"
        "from megatron_llm_tpu.observability import trace\n"
        "def tick(x, rec):\n"
        "    y = jnp.sum(x)\n"
        "    with trace.span('tick', val=y):\n"
        "        pass\n"
        "    rec.event('spec_tick', logits=jnp.exp(x))\n",
        "import jax.numpy as jnp\n"
        "from megatron_llm_tpu.observability import trace\n"
        "def tick(x, rec):\n"
        "    y = jnp.sum(x)\n"
        "    n = int(y)\n"
        "    with trace.span('tick', val=n):\n"
        "        pass\n"
        "    rec.event('spec_tick', emitted=len(x))\n",
    ),
    "line-length": (
        "x = 1  # " + "y" * 120 + "\n",
        "x = 1\n",
    ),
    "tabs": (
        "x = 1\t# tab\n",
        "x = 1  # spaces\n",
    ),
    "trailing-whitespace": (
        "x = 1   \n",
        "x = 1\n",
    ),
}


def test_every_rule_has_a_fixture():
    assert set(FIXTURES) | set(PROJECT_FIXTURES) == set(RULES_BY_ID), (
        "each rule needs positive/negative fixtures (per-file rules in "
        "FIXTURES, project rules in PROJECT_FIXTURES)")
    assert set(FIXTURES) == {r.id for r in ALL_RULES}
    assert set(PROJECT_FIXTURES) == {r.id for r in PROJECT_RULES}


@pytest.mark.parametrize("rule_id", sorted(FIXTURES))
def test_rule_positive(rule_id):
    bad, _good = FIXTURES[rule_id]
    path = ("observability/fixture.py" if rule_id == "obs-no-sync"
            else "fixture.py")
    hits = [f for f in findings_for(bad, path) if f.rule == rule_id]
    assert hits, f"{rule_id}: positive fixture produced no finding"


@pytest.mark.parametrize("rule_id", sorted(FIXTURES))
def test_rule_negative(rule_id):
    _bad, good = FIXTURES[rule_id]
    path = ("observability/fixture.py" if rule_id == "obs-no-sync"
            else "fixture.py")
    hits = [f for f in findings_for(good, path) if f.rule == rule_id]
    assert not hits, f"{rule_id}: negative fixture flagged: {hits}"


@pytest.mark.parametrize("rule_id", sorted(FIXTURES))
def test_rule_suppressed(rule_id):
    """Appending ``# graftcheck: noqa[rule]`` on each finding line
    silences exactly that rule's findings."""
    bad, _good = FIXTURES[rule_id]
    path = ("observability/fixture.py" if rule_id == "obs-no-sync"
            else "fixture.py")
    hits = [f for f in findings_for(bad, path) if f.rule == rule_id]
    lines = bad.splitlines()
    for ln in sorted({f.line for f in hits}):
        lines[ln - 1] += f"  # graftcheck: noqa[{rule_id}] — fixture"
    suppressed = "\n".join(lines) + "\n"
    left = [f for f in findings_for(suppressed, path)
            if f.rule == rule_id]
    assert not left, f"{rule_id}: noqa did not suppress: {left}"


@pytest.mark.parametrize("rule_id", sorted(FIXTURES))
def test_rule_baselined(rule_id):
    """A baseline entry keyed (path, rule, stripped line) absorbs the
    finding — it still appears, marked baselined, and does not fail."""
    bad, _good = FIXTURES[rule_id]
    path = ("observability/fixture.py" if rule_id == "obs-no-sync"
            else "fixture.py")
    fs = [f for f in findings_for(bad, path) if f.rule == rule_id]
    src_lines = bad.splitlines()
    entries = [{"path": path, "rule": rule_id,
                "line": src_lines[f.line - 1].strip(),
                "reason": "fixture grandfathering", "count": 99}
               for f in fs]
    all_fs = findings_for(bad, path)
    core.apply_baseline(
        all_fs, entries,
        lambda f: src_lines[f.line - 1]
        if 1 <= f.line <= len(src_lines) else "")
    for f in all_fs:
        if f.rule == rule_id:
            assert f.baselined, f"{rule_id}: baseline did not absorb {f}"


# ---------------------------------------------------------------------------
# (b) the historical bugs, reproduced
# ---------------------------------------------------------------------------


def test_historic_id_keyed_cached_jit():
    """PR 1: cached_jit keyed on id(cfg) — id recycling serves a stale
    program; rebuilt-but-equal configs recompile.  The recompile-hazard
    rule pins the pattern."""
    src = (
        "import jax\n"
        "_JIT_CACHE = {}\n"
        "def cached_jit(cfg, name, build, **kw):\n"
        "    key = (id(cfg), name)\n"
        "    fn = _JIT_CACHE.get(key)\n"
        "    if fn is None:\n"
        "        fn = jax.jit(build(), **kw)\n"
        "        _JIT_CACHE[key] = fn\n"
        "    return fn\n"
    )
    hits = [f for f in findings_for(src) if f.rule == "recompile-hazard"]
    assert len(hits) == 1 and hits[0].line == 4
    assert "id()" in hits[0].message


def test_ragged_metadata_in_cached_jit_statics_flagged():
    """ISSUE 11: per-tick ragged batch composition (spans / horizons /
    k_eff) in a cached_jit STATICS key compiles one executable per tick
    mix — the dispatch explosion the ragged kernel removes.  The
    recompile-hazard rule pins the pattern; composition must be a traced
    operand (generation/ragged.py contract)."""
    bad_inline = (
        "from megatron_llm_tpu.generation import generation as gen\n"
        "def tick_fn(self, spans, horizons):\n"
        "    return gen.cached_jit(\n"
        "        self.cfg, 'engine_ragged_tick',\n"
        "        ('engine_ragged_tick', self.max_slots, tuple(spans),\n"
        "         tuple(horizons)),\n"
        "        lambda: None)\n"
    )
    hits_inline = [f for f in findings_for(bad_inline)
                   if f.rule == "recompile-hazard"
                   and "ragged" in f.message]
    assert hits_inline, "ragged metadata in statics not flagged"
    # k_eff sneaking in as an attribute is caught too
    bad_attr = (
        "from megatron_llm_tpu.generation import generation as gen\n"
        "def tick_fn(self):\n"
        "    return gen.cached_jit(\n"
        "        self.cfg, 't', ('t', self.k_eff), lambda: None)\n"
    )
    assert [f for f in findings_for(bad_attr)
            if f.rule == "recompile-hazard" and "ragged" in f.message]
    # the engine's REAL statics (geometry capacities, dtypes, mesh) are
    # clean — capacities like prefill_rows are shapes, not composition
    good = (
        "from megatron_llm_tpu.generation import generation as gen\n"
        "def tick_fn(self, pre_rows):\n"
        "    return gen.cached_jit(\n"
        "        self.cfg, 'engine_ragged_tick',\n"
        "        ('engine_ragged_tick', self.max_slots, pre_rows,\n"
        "         self.pages_per_seq, str(self.pool.k.dtype)),\n"
        "        lambda: None)\n"
    )
    assert not [f for f in findings_for(good)
                if f.rule == "recompile-hazard"]


def test_historic_direct_shard_map_import():
    """The 8-failure jax-0.4.37 gap: every direct spelling is caught,
    and compat.py itself is exempt."""
    spellings = [
        f"from jax import {_SM}\n",
        f"import jax.experimental.{_SM}\n",
        f"from jax.experimental.{_SM} import {_SM}\n",
        f"from jax.experimental import {_SM}\n",
        f"fn = jax.{_SM}(f, mesh=m)\n",
        f"fn = jax.experimental.{_SM}.{_SM}(f)\n",
        "from jax.sharding import get_" + "abstract_mesh\n",
    ]
    for src in spellings:
        hits = [f for f in findings_for(src)
                if f.rule == "no-direct-shard-map"]
        assert len(hits) == 1, f"missed: {src!r} -> {hits}"
    exempt = findings_for(f"from jax.experimental.{_SM} import {_SM}\n",
                          path="megatron_llm_tpu/parallel/compat.py")
    assert not [f for f in exempt if f.rule == "no-direct-shard-map"]


def test_historic_sync_in_instrument():
    """A 'metrics' helper that drains per-step values with device_get
    inside the jitted step — the exact overlap-destroying shape PR 2
    banished to log boundaries."""
    src = (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def train_step(state, batch):\n"
        "    loss = (batch * state).sum()\n"
        "    record(float(loss))\n"
        f"    record(np.asarray(jax.{_DG}(loss)))\n"
        "    return state, loss\n"
    )
    hits = [f for f in findings_for(src) if f.rule == "sync-in-jit"]
    assert {f.line for f in hits} == {6, 7}
    # and the shard_map-body route sees the same violation
    src2 = (
        "from megatron_llm_tpu.parallel.compat import "
        + _SM + "\n"
        "def body(x):\n"
        "    return int(x.sum())\n"
        + f"fn = {_SM}(body, mesh=None, in_specs=None, out_specs=None)\n"
    )
    hits2 = [f for f in findings_for(src2) if f.rule == "sync-in-jit"]
    assert [f.line for f in hits2] == [3]


def test_historic_unguarded_shared_state():
    """The AsyncCheckpointSaver shape: a writer thread publishing an
    error field the caller reads bare.  Both directions are checked:
    guarded-attr access outside the lock AND calling a '# holds' method
    without it."""
    src = (
        "import threading\n"
        "class Saver:\n"
        "    def __init__(self):\n"
        "        self._err_lock = threading.Lock()\n"
        "        self._error = None  # guarded by _err_lock\n"
        "    def _write(self, e):\n"
        "        self._error = e\n"
        "    def _clear(self):  # holds _err_lock\n"
        "        self._error = None\n"
        "    def wait(self):\n"
        "        self._clear()\n"
        "    def wait_ok(self):\n"
        "        with self._err_lock:\n"
        "            self._clear()\n"
    )
    hits = [f for f in findings_for(src) if f.rule == "lock-discipline"]
    assert {f.line for f in hits} == {7, 11}


def test_historic_pinned_key_reuse():
    """The engine's bitwise-resume contract pins one PRNG key per
    request; consuming it twice (here: in a decode loop without
    fold_in/split) silently correlates the sampling stream."""
    src = (
        "import jax\n"
        "def decode(key, steps):\n"
        "    toks = []\n"
        "    for _ in range(steps):\n"
        "        toks.append(jax.random.categorical(key, logits))\n"
        "    return toks\n"
    )
    hits = [f for f in findings_for(src) if f.rule == "rng-key-reuse"]
    assert [f.line for f in hits] == [5]
    # the engine's actual per-step shape (fold_in on the pinned key) is
    # the documented-legal idiom and stays clean
    ok = (
        "import jax\n"
        "def decode(key, steps):\n"
        "    toks = []\n"
        "    for i in range(steps):\n"
        "        k = jax.random.fold_in(key, i)\n"
        "        toks.append(jax.random.categorical(k, logits))\n"
        "    return toks\n"
    )
    assert not [f for f in findings_for(ok) if f.rule == "rng-key-reuse"]


def test_historic_spec_draft_verify_key_reuse():
    """ISSUE 9: the speculative tick derives draft-sampling keys, an
    acceptance-uniform key, and the rejection-residual key from one
    per-(request, step) base.  The buggy shape — the rejection sampler
    re-consuming the key the acceptance uniforms already consumed — makes
    the residual draw perfectly correlated with the accept/reject coin,
    which silently biases the 'lossless' output distribution.  The rule
    must flag the reuse; the shipped disjoint-fold_in fan-out
    (speculative/verify.py) must stay clean."""
    bad = (
        "import jax\n"
        "def accept_and_emit(base_key, k, resid_logits):\n"
        "    u = jax.random.uniform(base_key, (k,))\n"
        "    tok = jax.random.categorical(base_key, resid_logits)\n"
        "    return u, tok\n"
    )
    hits = [f for f in findings_for(bad) if f.rule == "rng-key-reuse"]
    assert [f.line for f in hits] == [4]
    # the shipped shape: one fold_in per stream, each derived key
    # consumed exactly once
    ok = (
        "import jax\n"
        "ACCEPT_STREAM, EMIT_STREAM = 2, 3\n"
        "def accept_and_emit(base_key, k, resid_logits):\n"
        "    u = jax.random.uniform("
        "jax.random.fold_in(base_key, ACCEPT_STREAM), (k,))\n"
        "    tok = jax.random.categorical("
        "jax.random.fold_in(base_key, EMIT_STREAM), resid_logits)\n"
        "    return u, tok\n"
    )
    assert not [f for f in findings_for(ok) if f.rule == "rng-key-reuse"]


def test_docstring_prose_never_false_positives():
    """The _strip_comment bug class, pinned: the old line scanner
    flagged forbidden spellings inside string literals and observability
    docstrings; the AST rules must not."""
    obs = (
        f'"""This instrument never calls {_DG} or {_BUR}:\n'
        "syncing the device would destroy the overlap it measures.\n"
        '"""\n'
        f'BANNED = ("{_DG}", "{_BUR}")  # data, not calls\n'
        "x = 1\n"
    )
    fs = findings_for(obs, path="megatron_llm_tpu/observability/doc.py")
    assert not [f for f in fs if f.rule == "obs-no-sync"], fs
    sm = (
        f'"""jax.{_SM} is unavailable on the pinned 0.4.37; use\n'
        "parallel/compat.py instead.\n"
        '"""\n'
        f'SPELLING = "jax.experimental.{_SM}"\n'
    )
    fs = findings_for(sm)
    assert not [f for f in fs if f.rule == "no-direct-shard-map"], fs


# ---------------------------------------------------------------------------
# (c) CLI contract: JSON schema, exit codes, linter shim
# ---------------------------------------------------------------------------


def test_json_output_schema(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("x = 1  # TODO fix\n")
    rc = core.main(["--json", "--no-baseline", str(bad)])
    out = capsys.readouterr().out.strip()
    assert rc == 1
    assert len(out.splitlines()) == 1, "JSON mode must emit ONE line"
    doc = json.loads(out)
    assert doc["graftcheck"] == 1
    assert doc["exit"] == 1
    assert doc["files"] == 1
    assert isinstance(doc["seconds"], float)
    assert doc["changed_only"] is False
    assert doc["stale_baseline"] == []
    assert set(doc["counts"]) == {"total", "active", "info", "baselined",
                                  "stale_baseline"}
    assert doc["counts"]["total"] == 1
    (f,) = doc["findings"]
    assert set(f) == {"path", "line", "col", "rule", "message",
                      "baselined", "severity"}
    assert f["rule"] == "todo-owner" and f["line"] == 1
    assert f["severity"] == "error"
    assert len(doc["rules"]) == len(DEFAULT_RULES)


def test_exit_codes(tmp_path, capsys, monkeypatch):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert core.main(["--no-baseline", str(clean)]) == 0
    dirty = tmp_path / "dirty.py"
    dirty.write_text("x = 1\t\n")
    assert core.main(["--no-baseline", str(dirty)]) == 1

    class Boom(core.Rule):
        id = "boom"
        summary = "always crashes"

        def check(self, ctx):
            raise RuntimeError("kaboom")

    import tools.graftcheck.rules as rules_mod

    monkeypatch.setattr(rules_mod, "DEFAULT_RULES", [Boom()])
    assert core.main(["--no-baseline", str(clean)]) == 2
    capsys.readouterr()


def test_syntax_error_is_a_finding_not_a_crash(tmp_path, capsys):
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    rc = core.main(["--no-baseline", str(broken)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "parse-error" in out


def test_linter_shim_legacy_surface(tmp_path, capsys):
    """The shim keeps the old entry points: lint_file counts + prints,
    main() exits 0/1, and the legacy regex exports survive."""
    from tools import linter

    assert linter.SHARD_MAP_RE.search("jax." + _SM)
    assert linter._strip_comment("x  # jax." + _SM) == "x  "

    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    assert linter.lint_file(str(ok)) == 0
    assert linter.main([str(ok)]) == 0
    capsys.readouterr()

    bad = tmp_path / "bad.py"
    bad.write_text(f"from jax import {_SM}\n")
    assert linter.lint_file(str(bad)) == 1
    assert "compat" in capsys.readouterr().out
    assert linter.main([str(bad)]) == 1
    capsys.readouterr()


def test_update_baseline_roundtrip(tmp_path, capsys):
    """--update-baseline writes entries that then absorb the findings;
    reasons survive a rewrite."""
    bad = tmp_path / "bad.py"
    bad.write_text("x = 1  # TODO fix\n")
    bl = tmp_path / "baseline.json"
    rc = core.main(["--update-baseline", "--baseline", str(bl), str(bad)])
    assert rc == 0
    doc = json.loads(bl.read_text())
    assert len(doc["entries"]) == 1
    entry = doc["entries"][0]
    assert entry["rule"] == "todo-owner" and entry["reason"] == ""
    # fill the reason in (the committed-baseline contract) and re-run
    entry["reason"] = "legacy comment, tracked elsewhere"
    bl.write_text(json.dumps(doc))
    assert core.main(["--baseline", str(bl), str(bad)]) == 0
    # rewriting preserves the hand-written reason
    rc = core.main(["--update-baseline", "--baseline", str(bl), str(bad)])
    assert rc == 0
    doc2 = json.loads(bl.read_text())
    assert doc2["entries"][0]["reason"] == "legacy comment, tracked elsewhere"
    capsys.readouterr()


def test_tpu_watch_job_registered():
    """The graftcheck job is in the watch queue, bounded, with a
    predicate that reads the one-line JSON: an analyzer crash (rc 2, no
    summary) is 'not captured' (retried), findings are captured."""
    from tools.tpu_watch import JOBS, _graftcheck_ran

    by_name = {name: (cmd, bounded, pred)
               for name, cmd, bounded, pred in JOBS}
    assert "graftcheck" in by_name
    cmd, bounded, pred = by_name["graftcheck"]
    assert bounded, "graftcheck has no internal watchdog — needs timeout"
    assert "--json" in cmd and "tools.graftcheck" in " ".join(cmd)
    assert pred is _graftcheck_ran
    assert pred('{"graftcheck": 1, "exit": 0}')
    assert pred('noise\n{"graftcheck": 1, "exit": 1}')
    assert not pred("Traceback (most recent call last):\n  boom\n")
    assert not pred("")


# ---------------------------------------------------------------------------
# (e) project rules (ISSUE 14): multi-file fixtures + the fact cache
# ---------------------------------------------------------------------------

# rule id -> (positive file set, negative twin).  A file set maps
# relpath -> source; docs/guide/*.md entries feed the contract rules'
# documentation side.  The positive must yield >= 1 ERROR finding of
# the rule; the negative must yield none.
PROJECT_FIXTURES = {
    "lock-order": (
        {
            "pkg/cycle.py": (
                "import threading\n"
                "class Recorder:\n"
                "    def __init__(self, eng):\n"
                "        self._lock = threading.Lock()\n"
                "        self.eng = eng  # instance of Engine\n"
                "    def log(self):\n"
                "        with self._lock:\n"
                "            self.eng.poke()\n"
                "class Engine:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n"
                "        self.rec = Recorder(self)\n"
                "    def step(self):\n"
                "        with self._lock:\n"
                "            self.rec.log()\n"
                "    def poke(self):\n"
                "        with self._lock:\n"
                "            pass\n"),
        },
        {
            "pkg/cycle.py": (
                "import threading\n"
                "class Recorder:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n"
                "    def log(self):\n"
                "        with self._lock:\n"
                "            pass\n"
                "class Engine:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n"
                "        self.rec = Recorder()\n"
                "    def step(self):\n"
                "        with self._lock:\n"
                "            self.rec.log()\n"),
        },
    ),
    "wire-metrics": (
        {
            "megatron_llm_tpu/m.py": (
                "reg.counter('mlt_fix_undocumented_total')\n"
                "reg.gauge('mlt_fix_labeled_total',\n"
                "          labels={'right': 'x'})\n"),
            "docs/guide/fix.md": (
                "| metric | type | meaning |\n|---|---|---|\n"
                "| `mlt_fix_ghost_total` | counter | never registered |\n"
                "| `mlt_fix_labeled_total{wrong}` | gauge | bad labels |\n"),
        },
        {
            "megatron_llm_tpu/m.py":
                "reg.counter('mlt_fix_total', labels={'kind': 'a'})\n",
            "docs/guide/fix.md": (
                "| metric | type | meaning |\n|---|---|---|\n"
                "| `mlt_fix_total{kind}` | counter | fine |\n"),
        },
    ),
    "wire-health": (
        {
            "megatron_llm_tpu/server.py": (
                "class MegatronServer:\n"
                "    def health(self):\n"
                "        info = {'status': 'ok', 'extra': 1}\n"
                "        return info\n"),
            "megatron_llm_tpu/router.py": (
                "class ReplicaView:\n"
                "    @staticmethod\n"
                "    def parse(url, payload):\n"
                "        return (payload.get('status'),\n"
                "                payload.get('ghost'))\n"),
            "docs/guide/serving.md": (
                "### The /health payload\n\n"
                "| field | meaning |\n|---|---|\n"
                "| `status` | liveness |\n"
                "| `phantom` | stale row |\n"),
        },
        {
            "megatron_llm_tpu/server.py": (
                "class MegatronServer:\n"
                "    def health(self):\n"
                "        info = {'status': 'ok'}\n"
                "        return info\n"),
            "megatron_llm_tpu/router.py": (
                "class ReplicaView:\n"
                "    @staticmethod\n"
                "    def parse(url, payload):\n"
                "        return payload.get('status')\n"),
            "docs/guide/serving.md": (
                "### The /health payload\n\n"
                "| field | meaning |\n|---|---|\n"
                "| `status` | liveness |\n"),
        },
    ),
    "wire-flags": (
        {
            "pkg/arguments.py": (
                "from dataclasses import dataclass\n"
                "@dataclass\n"
                "class InferenceConfig:\n"
                "    undocumented_knob: int = 0\n"),
            "docs/guide/g.md": (
                "| knob | default |\n|---|---|\n"
                "| `--ghost_flag` | 0 |\n"),
        },
        {
            "pkg/arguments.py": (
                "from dataclasses import dataclass\n"
                "@dataclass\n"
                "class InferenceConfig:\n"
                "    real_knob: int = 0\n"),
            "docs/guide/g.md": (
                "| knob | default |\n|---|---|\n"
                "| `--real_knob` | 0 |\n"),
        },
    ),
}


def project_run(tmp_path, files, **kw):
    """Write a multi-file fixture under tmp_path and run the full
    two-pass analyzer over it (root = the fixture dir, no baseline)."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    kw.setdefault("baseline_path", None)
    return core.run([str(tmp_path)], root=str(tmp_path), **kw)


@pytest.mark.parametrize("rule_id", sorted(PROJECT_FIXTURES))
def test_project_rule_positive(rule_id, tmp_path):
    pos, _neg = PROJECT_FIXTURES[rule_id]
    res = project_run(tmp_path, pos)
    hits = [f for f in res.findings
            if f.rule == rule_id and f.severity == "error"]
    assert hits, f"{rule_id}: positive fixture produced no error finding"


@pytest.mark.parametrize("rule_id", sorted(PROJECT_FIXTURES))
def test_project_rule_negative(rule_id, tmp_path):
    _pos, neg = PROJECT_FIXTURES[rule_id]
    res = project_run(tmp_path, neg)
    hits = [f for f in res.findings
            if f.rule == rule_id and f.severity == "error"]
    assert not hits, f"{rule_id}: negative fixture flagged: " + \
        "\n".join(f.text() for f in hits)


def test_lockorder_cycle_fixture_details(tmp_path):
    """The seeded two-class cycle is reported as ONE deadlock finding
    naming both lock nodes, and the artifact exposes the cycle."""
    pos, _ = PROJECT_FIXTURES["lock-order"]
    res = project_run(tmp_path, pos)
    hits = [f for f in res.findings if f.rule == "lock-order"]
    assert len(hits) == 1
    assert "deadlock" in hits[0].message
    assert "Engine._lock" in hits[0].message
    assert "Recorder._lock" in hits[0].message
    lo = res.artifacts["lockorder"]
    assert lo["cycles"] == [["Engine._lock", "Recorder._lock"]]
    assert lo["order"] == []  # no topological order through a cycle


def test_lockorder_negative_fixture_has_order(tmp_path):
    _pos, neg = PROJECT_FIXTURES["lock-order"]
    res = project_run(tmp_path, neg)
    lo = res.artifacts["lockorder"]
    assert lo["cycles"] == []
    # the one-way nesting is discovered and ordered
    assert ("Engine._lock", "Recorder._lock") in {
        (e["from"], e["to"]) for e in lo["edges"]}
    assert lo["order"].index("Engine._lock") \
        < lo["order"].index("Recorder._lock")


def test_health_severities(tmp_path):
    """parsed-but-never-produced is an ERROR (the router routes on a
    default); produced-but-never-parsed is INFO (operator-facing)."""
    pos, _ = PROJECT_FIXTURES["wire-health"]
    res = project_run(tmp_path, pos)
    by_msg = {(f.severity, "ghost" in f.message, "extra" in f.message)
              for f in res.findings if f.rule == "wire-health"}
    assert ("error", True, False) in by_msg, "parsed-not-produced"
    assert any(sev == "info" and extra
               for sev, _g, extra in by_msg), "produced-not-parsed"
    # doc-table drift both ways
    msgs = [f.message for f in res.findings if f.rule == "wire-health"
            and f.severity == "error"]
    assert any("phantom" in m for m in msgs), "stale schema row"
    assert any("missing from" in m and "'extra'" in m for m in msgs), \
        "undocumented produced field"


def test_metrics_label_mismatch_fixture(tmp_path):
    pos, _ = PROJECT_FIXTURES["wire-metrics"]
    res = project_run(tmp_path, pos)
    msgs = [f.message for f in res.findings if f.rule == "wire-metrics"]
    assert any("label" in m and "mlt_fix_labeled_total" in m
               for m in msgs), msgs
    assert any("mlt_fix_ghost_total" in m for m in msgs)
    assert any("mlt_fix_undocumented_total" in m for m in msgs)


def test_project_rule_noqa_suppression(tmp_path):
    """A pass-2 finding anchored in a .py file honors the same noqa
    grammar as pass-1 findings."""
    pos, _ = PROJECT_FIXTURES["wire-health"]
    files = dict(pos)
    files["megatron_llm_tpu/router.py"] = (
        "class ReplicaView:\n"
        "    @staticmethod\n"
        "    def parse(url, payload):\n"
        "        return (payload.get('status'),\n"
        "                payload.get('ghost'))"
        "  # graftcheck: noqa[wire-health] — fixture\n")
    res = project_run(tmp_path, files)
    assert not [f for f in res.findings
                if f.rule == "wire-health" and "ghost" in f.message]


def test_project_rule_baseline_absorbs(tmp_path):
    """Baseline entries absorb pass-2 findings too (same key grammar),
    including ones anchored in markdown files."""
    pos, _ = PROJECT_FIXTURES["wire-health"]
    res = project_run(tmp_path, pos)
    errors = [f for f in res.findings
              if f.rule == "wire-health" and f.severity == "error"]
    assert errors
    entries = []
    for f in errors:
        text = (tmp_path / f.path).read_text().splitlines()[f.line - 1]
        entries.append({"path": f.path, "rule": f.rule,
                        "line": text.strip(), "reason": "fixture",
                        "count": 9})
    bl = tmp_path / "baseline.json"
    core.save_baseline(str(bl), entries)
    res2 = project_run(tmp_path, pos, baseline_path=str(bl))
    left = [f for f in res2.findings
            if f.rule == "wire-health" and f.severity == "error"
            and not f.baselined]
    assert not left, left


def test_stale_baseline_distinguishes_renamed_rule(tmp_path):
    """A baseline entry orphaned by a rule rename reads 'unknown-rule';
    one whose code was fixed reads 'unmatched' — the regression pinned
    by ISSUE 14's small-fix satellite."""
    f = tmp_path / "clean.py"
    f.write_text("x = 1\n")
    bl = tmp_path / "baseline.json"
    core.save_baseline(str(bl), [
        {"path": "clean.py", "rule": "old-rule-name",
         "line": "x = 1", "reason": "r"},
        {"path": "clean.py", "rule": "todo-owner",
         "line": "x = 1  # TODO fix", "reason": "r"},
    ])
    res = core.run([str(f)], root=str(tmp_path), baseline_path=str(bl))
    kinds = {(e["rule"], e["stale_kind"]) for e in res.stale_baseline}
    assert ("old-rule-name", "unknown-rule") in kinds
    assert ("todo-owner", "unmatched") in kinds


def test_changed_only_scopes_pass1_not_pass2(tmp_path):
    """--changed-only: per-file findings only for changed files, but the
    cross-file analyses still see the WHOLE project through the fact
    cache; stale-baseline detection is off (absence proves nothing)."""
    pos, _ = PROJECT_FIXTURES["wire-health"]
    files = dict(pos)
    files["megatron_llm_tpu/todo.py"] = "x = 1  # TODO fix\n"
    cache = tmp_path / "cache.json"
    full = project_run(tmp_path, files, fact_cache_path=str(cache))
    assert any(f.rule == "todo-owner" for f in full.findings)
    assert any(f.rule == "wire-health" for f in full.findings)
    assert cache.exists()

    res = core.run([str(tmp_path)], root=str(tmp_path),
                   baseline_path=None, changed_files=[],
                   fact_cache_path=str(cache))
    assert res.changed_only
    assert not [f for f in res.findings if f.rule == "todo-owner"]
    assert [f for f in res.findings if f.rule == "wire-health"]
    assert res.stale_baseline == []

    res2 = core.run([str(tmp_path)], root=str(tmp_path),
                    baseline_path=None,
                    changed_files=["megatron_llm_tpu/todo.py"],
                    fact_cache_path=str(cache))
    assert [f for f in res2.findings if f.rule == "todo-owner"]
    assert [f for f in res2.findings if f.rule == "wire-health"]


def test_changed_only_cache_invalidates_on_content(tmp_path):
    """The cache-invalidation rule: entries are keyed by content sha256,
    so a file that changed WITHOUT being reported as changed is still
    re-collected — the cache can go stale, the analysis cannot."""
    pos, _ = PROJECT_FIXTURES["wire-health"]
    cache = tmp_path / "cache.json"
    project_run(tmp_path, pos, fact_cache_path=str(cache))
    # the producer starts emitting 'ghost' — but we *lie* and report
    # nothing changed; the sha mismatch must recollect anyway
    (tmp_path / "megatron_llm_tpu/server.py").write_text(
        "class MegatronServer:\n"
        "    def health(self):\n"
        "        info = {'status': 'ok', 'extra': 1, 'ghost': 2}\n"
        "        return info\n")
    res = core.run([str(tmp_path)], root=str(tmp_path),
                   baseline_path=None, changed_files=[],
                   fact_cache_path=str(cache))
    # the parsed-but-never-produced error is gone (facts recollected);
    # the new 'ghost missing from the schema table' finding replaces it
    assert not [f for f in res.findings
                if f.rule == "wire-health" and "ghost" in f.message
                and "parsed by ReplicaView" in f.message]
    assert [f for f in res.findings
            if f.rule == "wire-health" and "ghost" in f.message
            and "missing from" in f.message]


# ---------------------------------------------------------------------------
# (f) the full-repo sweep — tier-1 gate (+ anti-vacuity pins)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def repo_sweep():
    """ONE full two-pass sweep shared by the gate + anti-vacuity tests."""
    targets = [os.path.join(REPO, t)
               for t in ("megatron_llm_tpu", "tools", "tasks", "tests")]
    return core.run(targets, root=REPO)


def test_repo_sweep_clean(repo_sweep):
    """`python -m tools.graftcheck megatron_llm_tpu tools tasks tests`
    on this tree: zero non-baselined error findings, inside the 30 s
    budget, with the full two-pass rule set."""
    assert len(ALL_RULES) >= 7
    ported = {"todo-owner", "obs-no-sync", "no-direct-shard-map"}
    new = {"sync-in-jit", "lock-discipline", "rng-key-reuse",
           "recompile-hazard"}
    project = {"lock-order", "wire-metrics", "wire-health", "wire-flags"}
    assert ported | new | project <= set(RULES_BY_ID)
    result = repo_sweep
    active = result.active
    assert not active, "new findings (fix, noqa with a reason, or " \
        "baseline with a reason):\n" + "\n".join(f.text() for f in active)
    assert not result.stale_baseline, (
        "baseline entries whose code was fixed — delete them: "
        f"{result.stale_baseline}")
    assert result.seconds < 30, f"sweep took {result.seconds:.1f}s"
    assert result.files > 150  # really swept the tree


def test_lock_graph_engine_recorder_edge(repo_sweep):
    """Anti-vacuity: the PR 12 engine→recorder ordering is ANALYZED —
    the edge must exist in the derived graph, the graph must be
    cycle-free with a total order, and the shared-lock annotation must
    have merged every RequestRecord node into the recorder's."""
    lo = repo_sweep.artifacts["lockorder"]
    edges = {(e["from"], e["to"]) for e in lo["edges"]}
    assert ("ContinuousBatchingEngine._lock",
            "FlightRecorder._lock") in edges
    assert lo["cycles"] == []
    assert lo["order"], "acyclic graph must have a topological order"
    assert len(lo["nodes"]) >= 15, "lock model shrank — extraction bug?"
    assert not any("RequestRecord" in n["id"] for n in lo["nodes"])
    rec = next(n for n in lo["nodes"]
               if n["id"] == "FlightRecorder._lock")
    assert "RequestRecord._lock" in rec["aliases"]
    # engine _work is the Condition alias of _lock, merged
    eng = next(n for n in lo["nodes"]
               if n["id"] == "ContinuousBatchingEngine._lock")
    assert "ContinuousBatchingEngine._work" in eng["aliases"]


def test_lockorder_committed_evidence(repo_sweep):
    """tools/graftcheck/lockorder.json is reviewed evidence (like the
    BENCH files): it must equal the graph derived from THIS tree."""
    with open(os.path.join(REPO, "tools", "graftcheck",
                           "lockorder.json")) as f:
        committed = json.load(f)
    assert committed == repo_sweep.artifacts["lockorder"], (
        "lock graph drifted from the committed evidence — regenerate: "
        "python -m tools.graftcheck --lockorder-out "
        "tools/graftcheck/lockorder.json megatron_llm_tpu tools tasks "
        "tests")


def test_contract_extractors_not_vacuous(repo_sweep):
    """An extraction regression must not pass as '0 findings': the
    sweep must actually SEE the repo's metric registrations, /health
    producer/consumer keys, and flag surfaces."""
    m = repo_sweep.artifacts["wire-metrics"]
    assert m["registered"] >= 60, m
    assert m["documented"] >= 55, m
    h = repo_sweep.artifacts["wire-health"]
    assert h["produced"] >= 35, h
    assert h["consumed"] >= 20, h
    assert h["documented"] >= 20, h
    fl = repo_sweep.artifacts["wire-flags"]
    assert fl["inference_fields"] >= 20, fl
    assert fl["code_flags"] >= 250, fl
    assert fl["doc_flags"] >= 80, fl


def test_baseline_entries_all_explained():
    """Zero unexplained entries: every committed baseline entry carries
    a nonempty human reason."""
    entries = core.load_baseline(core.BASELINE_DEFAULT)
    unexplained = [e for e in entries if not e.get("reason", "").strip()]
    assert not unexplained, unexplained


def test_lock_rule_verifies_engine_annotations():
    """The engine's 20-attribute lock model really is loaded (an empty
    model would make the repo sweep vacuously clean)."""
    import ast as ast_mod

    from tools.graftcheck.rules.locks import LockDisciplineRule

    path = os.path.join(REPO, "megatron_llm_tpu", "generation",
                        "engine.py")
    ctx = core.FileContext(path)
    rule = LockDisciplineRule()
    for node in ast_mod.walk(ctx.tree):
        if isinstance(node, ast_mod.ClassDef) \
                and node.name == "ContinuousBatchingEngine":
            model = rule._build(ctx, node)
            assert model is not None
            assert {"_queue", "_slots", "_committed",
                    "_stopping"} <= set(model.guards)
            assert "_retire" in model.holds
            assert "_work" in model.groups.get("_lock", set())
            return
    raise AssertionError("engine class not found")


def test_lock_rule_verifies_router_annotations():
    """ISSUE 10: the router's cross-thread state (breaker fields on
    Replica, the fleet dict on ReplicaRegistry, the server's /health seq
    counter) is lock-annotated and really modeled by the rule — the repo
    sweep's cleanliness over serving/router/ is not vacuous."""
    import ast as ast_mod

    from tools.graftcheck.rules.locks import LockDisciplineRule

    rule = LockDisciplineRule()
    expected = {
        os.path.join(REPO, "megatron_llm_tpu", "serving", "router",
                     "registry.py"): {
            "Replica": ({"_state", "_failures", "_view", "_draining"},
                        {"_advance_failure_locked"}),
            "ReplicaRegistry": ({"_replicas"}, set()),
        },
        os.path.join(REPO, "megatron_llm_tpu", "generation",
                     "server.py"): {
            "MegatronServer": ({"_health_seq"}, set()),
        },
    }
    for path, classes in expected.items():
        ctx = core.FileContext(path)
        found = set()
        for node in ast_mod.walk(ctx.tree):
            if isinstance(node, ast_mod.ClassDef) and node.name in classes:
                guards, holds = classes[node.name]
                model = rule._build(ctx, node)
                assert model is not None, f"{node.name}: no lock model"
                assert guards <= set(model.guards), (
                    f"{node.name} missing guards: "
                    f"{guards - set(model.guards)}")
                assert holds <= set(model.holds)
                found.add(node.name)
        assert found == set(classes), f"{path}: missing {set(classes) - found}"


def test_traced_functions_really_analyzed():
    """sync-in-jit resolves the engine's cached_jit builders — the four
    compiled programs are in the analyzed set (a resolution regression
    would silently stop checking the hot path)."""
    from tools.graftcheck.rules.sync import SyncInJitRule

    path = os.path.join(REPO, "megatron_llm_tpu", "generation",
                        "engine.py")
    ctx = core.FileContext(path)
    names = {getattr(n, "name", "<lambda>")
             for n in SyncInJitRule()._traced_nodes(ctx)}
    assert {"tick", "prefill", "chunk", "copy"} <= names
