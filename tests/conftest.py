"""Test harness: 8 virtual CPU devices (reference tests need >=8 real GPUs
under torchrun — tests/test_utilities.py:6; we simulate the mesh on CPU,
which the reference cannot do)."""

import os

# Must be set before jax is imported anywhere. Force (not setdefault): the
# axon TPU tunnel env presets JAX_PLATFORMS=axon and registers the tunnel in
# every python process via sitecustomize when PALLAS_AXON_POOL_IPS is set —
# tests must run hermetically on the virtual CPU mesh.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
