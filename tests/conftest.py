"""Test harness: 8 virtual CPU devices (reference tests need >=8 real GPUs
under torchrun — tests/test_utilities.py:6; we simulate the mesh on CPU,
which the reference cannot do)."""

# Must run before any jax backend init: tests are hermetic on an 8-device
# virtual CPU mesh even when the axon TPU tunnel env is present.
import os

# compile-only TPU topology clients (tests/test_aot_scale.py) grab the
# libtpu lockfile; allow coexistence with other local libtpu users
os.environ.setdefault("ALLOW_MULTIPLE_LIBTPU_LOAD", "true")

from megatron_llm_tpu.utils.platform import pin_cpu_platform  # noqa: E402

pin_cpu_platform(n_devices=8)

import pytest  # noqa: E402

# Fast/slow lanes (round-3 VERDICT item 7): the default `pytest -q` lane
# skips these (pytest.ini addopts -m "not slow"), keeping it ~5 min on a
# single core; `pytest -q -m ""` runs the full ~30-min matrix. The list
# is data (tests/slow_tests.txt, regenerated from a --durations=0 run:
# call > 6 s) so explicit @pytest.mark.slow decorations still compose.
_SLOW_FILE = os.path.join(os.path.dirname(__file__), "slow_tests.txt")
with open(_SLOW_FILE) as _f:
    _SLOW_NODES = {line.strip() for line in _f
                   if line.strip() and not line.startswith("#")}


def pytest_collection_modifyitems(config, items):
    for item in items:
        base = item.nodeid.split("[")[0]
        if base in _SLOW_NODES:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
