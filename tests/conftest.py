"""Test harness: 8 virtual CPU devices (reference tests need >=8 real GPUs
under torchrun — tests/test_utilities.py:6; we simulate the mesh on CPU,
which the reference cannot do)."""

import os

# Must be set before jax is imported anywhere. Force (not setdefault): the
# axon TPU tunnel env presets JAX_PLATFORMS=axon and registers the tunnel in
# every python process via sitecustomize when PALLAS_AXON_POOL_IPS is set —
# tests must run hermetically on the virtual CPU mesh.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

# The axon sitecustomize registers its PJRT plugin at interpreter startup
# (before conftest runs), which wins over the env var — pin the platform via
# jax.config too, which takes effect as long as no backend is initialized yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
