"""Test harness: 8 virtual CPU devices (reference tests need >=8 real GPUs
under torchrun — tests/test_utilities.py:6; we simulate the mesh on CPU,
which the reference cannot do)."""

# Must run before any jax backend init: tests are hermetic on an 8-device
# virtual CPU mesh even when the axon TPU tunnel env is present.
import os

# compile-only TPU topology clients (tests/test_aot_scale.py) grab the
# libtpu lockfile; allow coexistence with other local libtpu users
os.environ.setdefault("ALLOW_MULTIPLE_LIBTPU_LOAD", "true")

from megatron_llm_tpu.utils.platform import pin_cpu_platform  # noqa: E402

pin_cpu_platform(n_devices=8)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
