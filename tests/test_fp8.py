"""FP8 matmul path (ops/fp8.py) — the TransformerEngine-analog
(reference transformer.py:1009-1028, arguments.py:372-392 --fp8_* flags).

Discipline mirrors the reference's fused-kernel tests: quantized ops vs the
unquantized computation within format-appropriate tolerances, plus an
end-to-end training check.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.models import init_model_params, make_config
from megatron_llm_tpu.models.language_model import loss_from_batch
from megatron_llm_tpu.ops.fp8 import E4M3, E5M2, fp8_dot, fp8_linear, quantize


def test_quantize_round_trip():
    x = jax.random.normal(jax.random.PRNGKey(0), (128, 64)) * 7.3
    for dtype, rel in ((E4M3, 0.07), (E5M2, 0.14)):
        x_q, inv_scale = quantize(x, dtype)
        back = x_q.astype(jnp.float32) * inv_scale
        err = np.abs(np.asarray(back - x)) / (np.abs(np.asarray(x)) + 1e-3)
        assert err.max() < rel, (dtype, err.max())
    # margin backs the scale off by 2^-margin
    _, s0 = quantize(x, E4M3, margin=0)
    _, s2 = quantize(x, E4M3, margin=2)
    np.testing.assert_allclose(float(s2) / float(s0), 4.0, rtol=1e-6)


def test_fp8_dot_forward_error():
    x = jax.random.normal(jax.random.PRNGKey(0), (32, 128))
    w = jax.random.normal(jax.random.PRNGKey(1), (128, 64))
    y = jax.jit(lambda a, b: fp8_dot(a, b))(x, w)
    ref = x @ w
    # e4m3 has ~2 mantissa-bit precision: relative error vs the |x||w| scale
    denom = np.abs(np.asarray(x)).max() * np.abs(np.asarray(w)).max() * 128
    assert float(jnp.abs(y - ref).max()) / denom < 0.02


@pytest.mark.parametrize("hybrid", [True, False])
def test_fp8_dot_grads_close_to_exact(hybrid):
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 64))
    w = jax.random.normal(jax.random.PRNGKey(1), (64, 32))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (16, 32))

    def loss_fp8(x_, w_):
        return jnp.sum((fp8_dot(x_, w_, hybrid) - tgt) ** 2)

    def loss_ref(x_, w_):
        return jnp.sum((x_ @ w_ - tgt) ** 2)

    gx, gw = jax.grad(loss_fp8, (0, 1))(x, w)
    rx, rw = jax.grad(loss_ref, (0, 1))(x, w)
    for g, r in ((gx, rx), (gw, rw)):
        cos = float(
            jnp.vdot(g, r) / (jnp.linalg.norm(g) * jnp.linalg.norm(r))
        )
        assert cos > 0.99, f"fp8 grad diverges from exact (cos={cos})"


def test_fp8_linear_glu_kernel_shape():
    p = {"kernel": jax.random.normal(jax.random.PRNGKey(0), (64, 2, 96))}
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 64))
    y = fp8_linear(p, x)
    assert y.shape == (4, 8, 2, 96)
    ref = jnp.einsum("...h,hcf->...cf", x, p["kernel"])
    rel_rms = float(jnp.linalg.norm(y - ref) / jnp.linalg.norm(ref))
    assert rel_rms < 0.05, rel_rms


def test_fp8_model_trains():
    """A tiny llama with fp8 hybrid matmuls memorizes a fixed batch; loss
    path, custom vjp, and GLU integration all exercised end to end."""
    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, vocab_size=256, seq_length=32,
        max_position_embeddings=64, params_dtype="float32",
        use_flash_attn=False, fp8="hybrid",
    )
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0, 256)
    batch = {"tokens": tok[:, :-1], "labels": tok[:, 1:],
             "loss_mask": jnp.ones((2, 32), jnp.float32)}

    @jax.jit
    def step(p):
        loss, g = jax.value_and_grad(
            lambda q: loss_from_batch(cfg, q, batch)[0]
        )(p)
        return loss, jax.tree.map(lambda w, gg: w - 0.3 * gg, p, g)

    losses = []
    p = params
    for _ in range(60):
        loss, p = step(p)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, losses[::10]


def test_fp8_vs_bf16_logits_close():
    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, vocab_size=256, seq_length=32,
        max_position_embeddings=64, params_dtype="float32",
        use_flash_attn=False,
    )
    from megatron_llm_tpu.models import model_forward

    params = init_model_params(cfg, jax.random.PRNGKey(0))
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 256)
    ref, _ = model_forward(cfg, params, tok)
    cfg.model.fp8 = "e4m3"
    got, _ = model_forward(cfg, params, tok)
    # same ballpark as the reference's bf16-vs-fp32 gate (<=0.1 avg err,
    # getting_started.md:152-155) — fp8 is coarser, gate on avg abs err
    avg = float(jnp.abs(got - ref).mean())
    assert avg < 0.2, avg


def test_fp8_tp_parity():
    """fp8 quantization under tensor parallelism: the per-tensor amax is a
    global reduction under GSPMD, so tp=2 must reproduce the unsharded
    loss/grads (a sharding-local amax would silently change the scales)."""
    from megatron_llm_tpu.core.parallel_state import build_mesh, global_mesh
    from megatron_llm_tpu.parallel.tp import batch_shardings, param_shardings

    common = dict(
        num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, vocab_size=256, seq_length=32,
        max_position_embeddings=64, params_dtype="float32",
        use_flash_attn=False, fp8="hybrid",
    )
    cfg = make_config("llama2", **common)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0, 256)
    batch = {"tokens": tok[:, :-1], "labels": tok[:, 1:],
             "loss_mask": jnp.ones((2, 32), jnp.float32)}

    def run(mesh, cfg):
        with global_mesh(mesh):
            p = jax.device_put(params, param_shardings(mesh, params))
            b = jax.device_put(batch, batch_shardings(cfg, mesh, batch))
            loss, grads = jax.jit(jax.value_and_grad(
                lambda q: loss_from_batch(cfg, q, b)[0]
            ))(p, )
            return float(loss), jax.device_get(grads)

    ref_loss, ref_grads = run(build_mesh(devices=jax.devices()[:1]), cfg)
    cfg2 = make_config("llama2", **common, tensor_model_parallel_size=2)
    loss, grads = run(build_mesh(tensor_model_parallel_size=2,
                                 devices=jax.devices()[:2]), cfg2)
    np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(ref_grads),
                    jax.tree_util.tree_leaves(grads)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-4, atol=5e-4)
