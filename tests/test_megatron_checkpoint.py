"""Reference-checkpoint interop: torch-free .pt reader + TP/PP shard merge
(reference checkpointing.py:77-104 layout; VERDICT missing #4).

The synthetic checkpoint is WRITTEN with torch.save (the real serializer the
reference uses) and READ with our zipfile+pickle reader — a true round trip
over the wire format."""

import argparse
import os

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from megatron_llm_tpu.models import model_forward
from weights_conversion.hf_to_native import (
    config_from_hf,
    convert_hf_model,
    pack_qkv,
)
from weights_conversion.megatron_to_native import (
    convert_megatron_state,
    load_reference_state,
)
from weights_conversion.permute_qkv import hf_rows_to_interleaved
from weights_conversion.pt_reader import load_pt


def tiny_hf_llama(vocab=128):
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=vocab, hidden_size=64, intermediate_size=176,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5,
        tie_word_embeddings=False, attn_implementation="eager",
    )
    torch.manual_seed(7)
    return LlamaForCausalLM(cfg)


def build_reference_checkpoint(hf, cfg, out_dir, tp=2, pp=2, iteration=100):
    """Write the HF weights in the reference's sharded on-disk layout."""
    m = cfg.model
    n, nkv, d, h = (m.num_attention_heads, m.num_attention_heads_kv,
                    m.kv_channels, m.hidden_size)
    L, lpr = m.num_layers, m.num_layers // pp
    sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}

    def W(i, name):
        return sd[f"model.layers.{i}.{name}.weight"]

    iter_dir = os.path.join(out_dir, f"iter_{iteration:07d}")
    for t in range(tp):
        for p in range(pp):
            enc = {}
            for local in range(lpr):
                gi = p * lpr + local
                # megatron fused qkv = native kernel transposed; column-split
                # over tp keeps whole kv groups per rank
                qkv = pack_qkv(
                    hf_rows_to_interleaved(W(gi, "self_attn.q_proj"), d),
                    hf_rows_to_interleaved(W(gi, "self_attn.k_proj"), d),
                    W(gi, "self_attn.v_proj"), n, nkv, d,
                ).T
                rows = qkv.shape[0] // tp
                enc[f"layers.{local}.attention.query_key_value.weight"] = (
                    torch.from_numpy(qkv[t * rows:(t + 1) * rows].copy())
                )
                dense = W(gi, "self_attn.o_proj")
                cols = dense.shape[1] // tp
                enc[f"layers.{local}.attention.dense.weight"] = (
                    torch.from_numpy(dense[:, t * cols:(t + 1) * cols].copy())
                )
                up, gate = W(gi, "mlp.up_proj"), W(gi, "mlp.gate_proj")
                ffn_loc = up.shape[0] // tp
                enc[f"layers.{local}.mlp.dense_h_to_4h.weight"] = (
                    torch.from_numpy(np.concatenate([
                        up[t * ffn_loc:(t + 1) * ffn_loc],
                        gate[t * ffn_loc:(t + 1) * ffn_loc],
                    ], axis=0))
                )
                down = sd[f"model.layers.{gi}.mlp.down_proj.weight"]
                cols = down.shape[1] // tp
                enc[f"layers.{local}.mlp.dense_4h_to_h.weight"] = (
                    torch.from_numpy(down[:, t * cols:(t + 1) * cols].copy())
                )
                enc[f"layers.{local}.input_layernorm.weight"] = (
                    torch.from_numpy(W(gi, "input_layernorm").copy())
                )
                enc[f"layers.{local}.post_attention_layernorm.weight"] = (
                    torch.from_numpy(W(gi, "post_attention_layernorm").copy())
                )
            lm = {"encoder": enc}
            if p == 0:
                emb = sd["model.embed_tokens.weight"]
                rows = emb.shape[0] // tp
                lm["embedding"] = {"word_embeddings": {
                    "weight": torch.from_numpy(
                        emb[t * rows:(t + 1) * rows].copy())
                }}
            if p == pp - 1:
                enc["final_layernorm.weight"] = torch.from_numpy(
                    sd["model.norm.weight"].copy())
                head = sd["lm_head.weight"]
                rows = head.shape[0] // tp
                lm["lm_head"] = torch.from_numpy(
                    head[t * rows:(t + 1) * rows].copy())
            name = f"mp_rank_{t:02d}" + (f"_{p:03d}" if pp > 1 else "")
            rank_dir = os.path.join(iter_dir, name)
            os.makedirs(rank_dir, exist_ok=True)
            torch.save(
                {"model": {"language_model": lm}, "iteration": iteration,
                 "args": argparse.Namespace(tensor_model_parallel_size=tp),
                 "rng_state": [{"random_rng_state": ("MT19937", 0)}]},
                os.path.join(rank_dir, "model_optim_rng.pt"),
            )
    with open(os.path.join(out_dir, "latest_checkpointed_iteration.txt"),
              "w") as f:
        f.write(str(iteration))


def test_pt_reader_basic(tmp_path):
    """Torch-free reader returns numpy arrays matching what torch saved."""
    obj = {
        "a": torch.arange(12, dtype=torch.float32).reshape(3, 4),
        "nested": {"b": torch.ones(5, dtype=torch.int64) * 7},
        "half": torch.full((2, 2), 1.5, dtype=torch.bfloat16),
        "scalar": torch.tensor(3.0),
        "args": argparse.Namespace(lr=0.1),
    }
    p = tmp_path / "x.pt"
    torch.save(obj, p)
    loaded = load_pt(str(p))
    np.testing.assert_array_equal(loaded["a"], obj["a"].numpy())
    np.testing.assert_array_equal(loaded["nested"]["b"], obj["nested"]["b"].numpy())
    assert float(loaded["scalar"]) == 3.0
    assert loaded["half"].astype(np.float32).max() == 1.5
    assert loaded["args"].lr == 0.1


def test_pt_reader_noncontiguous(tmp_path):
    """Stride/offset handling: tensors saved as views."""
    base = torch.arange(24, dtype=torch.float32).reshape(4, 6)
    obj = {"t": base.t()}  # transposed view: non-trivial strides
    p = tmp_path / "v.pt"
    torch.save(obj, p)
    loaded = load_pt(str(p))
    np.testing.assert_array_equal(loaded["t"], base.t().numpy())


@pytest.mark.parametrize("tp,pp", [(1, 1), (2, 2)])
def test_reference_checkpoint_round_trip(tmp_path, tp, pp):
    hf = tiny_hf_llama()
    cfg = config_from_hf(hf.config, "llama2")
    cfg.training.params_dtype = "float32"
    cfg.training.use_flash_attn = False
    expected = convert_hf_model(hf, cfg)

    build_reference_checkpoint(hf, cfg, str(tmp_path), tp=tp, pp=pp)
    states, tp_found, pp_found = load_reference_state(str(tmp_path))
    assert (tp_found, pp_found) == (tp, pp)
    params = convert_megatron_state(states, cfg)

    import jax.tree_util as jtu

    got = {jtu.keystr(k): v for k, v in
           jtu.tree_flatten_with_path(params)[0]}
    for path, val in jtu.tree_flatten_with_path(expected)[0]:
        key = jtu.keystr(path)
        np.testing.assert_allclose(
            got[key], val, atol=1e-6, err_msg=key)

    # end to end: merged params produce HF-parity logits
    tokens = np.random.RandomState(0).randint(0, 128, (1, 32)).astype(np.int32)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens.astype(np.int64))).logits.numpy()
    ours, _ = model_forward(cfg, params, tokens)
    ours = np.asarray(ours, np.float32)[..., :128]
    err = np.abs(ours - hf_logits).max(axis=-1).mean()
    assert err <= 1e-3, f"avg max logit err {err}"


def test_pt_reader_ancient_fp16_loss_scaler(tmp_path):
    """Ancient reference checkpoints pickle their loss scaler from the
    pre-refactor top-level ``fp16.loss_scaler`` module (the case reference
    checkpointing.py:487-499 handles with a sys.modules alias). The
    torch-free reader must load them — scaler stubbed, never executed —
    and ``extract_loss_scale`` recovers cur_scale (closes the round-3
    fp16_deprecated descope)."""
    import sys
    import types

    fp16_mod = types.ModuleType("fp16")
    ls_mod = types.ModuleType("fp16.loss_scaler")

    class DynamicLossScaler:
        def __init__(self):
            self.cur_scale = 4096.0
            self.cur_iter = 17
            self.scale_factor = 2.0

    # pickle resolves classes by (module, qualname): make it look exactly
    # like the ancient top-level class
    DynamicLossScaler.__module__ = "fp16.loss_scaler"
    DynamicLossScaler.__qualname__ = "DynamicLossScaler"
    ls_mod.DynamicLossScaler = DynamicLossScaler
    fp16_mod.loss_scaler = ls_mod
    sys.modules["fp16"] = fp16_mod
    sys.modules["fp16.loss_scaler"] = ls_mod
    try:
        obj = {
            "model": {"word_embeddings.weight": torch.arange(6.0).reshape(2, 3)},
            "optimizer": {"loss_scaler": DynamicLossScaler(), "step": 17},
            "iteration": 80000,
        }
        p = tmp_path / "ancient.pt"
        torch.save(obj, str(p))
    finally:
        del sys.modules["fp16"], sys.modules["fp16.loss_scaler"]

    from weights_conversion.pt_reader import extract_loss_scale, load_pt

    state = load_pt(str(p))
    np.testing.assert_allclose(state["model"]["word_embeddings.weight"],
                               [[0, 1, 2], [3, 4, 5]])
    assert state["iteration"] == 80000
    assert extract_loss_scale(state) == 4096.0
    # a scaler-free checkpoint reports None, not a fabricated scale
    assert extract_loss_scale({"model": {}, "optimizer": {"step": 1}}) is None
