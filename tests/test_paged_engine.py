"""Continuous-batching engine + paged KV cache tests (ISSUE 1).

Gates: (1) the paged decode path is numerically IDENTICAL to the dense-cache
decode path — bitwise for greedy tokens/logits on CPU; (2) the block-table
allocator never leaks or double-books pages under churn; (3) per-slot
sampling is a function of (request seed, step) alone, not slot placement;
(4) the compiled-program cache keys on config CONTENT, not object identity.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.generation import (
    ContinuousBatchingEngine,
    generate_tokens,
)
from megatron_llm_tpu.generation.generation import (
    _JIT_CACHE,
    cached_jit,
    clear_jit_cache,
    config_fingerprint,
    init_kv_caches,
)
from megatron_llm_tpu.generation.sampling import (
    modify_logits_for_top_k_filtering,
    modify_logits_for_top_p_filtering,
    sample,
    sample_per_slot,
)
from megatron_llm_tpu.models import init_model_params, make_config
from megatron_llm_tpu.models.language_model import (
    _compute_dtype,
    make_rope_cache,
    model_forward,
)
from megatron_llm_tpu.ops.paged_attention import (
    PagedState,
    paged_attention_decode,
)

VOCAB = 67


class ToyTokenizer:
    eod = 0
    bos = 1
    vocab_size = VOCAB

    def tokenize(self, text):
        return [2 + (ord(c) % (VOCAB - 2)) for c in text]

    def detokenize(self, ids):
        return "".join(chr(97 + (i % 26)) for i in ids if i >= 2)


@pytest.fixture(scope="module")
def toy_model():
    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, ffn_hidden_size=128, seq_length=128,
        max_position_embeddings=256, vocab_size=VOCAB,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype="float32", use_flash_attn=False,
    )
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


# ---------------------------------------------------------------------------
# Kernel / op level
# ---------------------------------------------------------------------------


def test_paged_kernel_interpret_matches_fallback():
    """The Pallas decode kernel (interpret mode) == the jnp gather fallback,
    with and without a sliding window."""
    from megatron_llm_tpu.ops.pallas.paged_attention import paged_decode_kernel

    rng = np.random.default_rng(0)
    b, n, nkv, d = 3, 4, 2, 64
    P, page, maxp = 9, 8, 4
    q = jnp.asarray(rng.normal(size=(b, 1, n, d)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(P, page, nkv, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(P, page, nkv, d)), jnp.float32)
    bt = jnp.asarray(rng.integers(1, P, size=(b, maxp)), jnp.int32)
    pos = jnp.asarray([5, 17, 30], jnp.int32)

    for sw in (None, 9):
        ref = paged_attention_decode(q, kp, vp, bt, pos,
                                     sliding_window=sw, use_kernel=False)
        ker = paged_decode_kernel(q, kp, vp, bt, pos, scale=1.0 / d ** 0.5,
                                  sliding_window=sw, interpret=True)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(ker),
                                   atol=2e-6, rtol=2e-6)


def test_dense_vs_paged_model_forward_bitwise(toy_model):
    """Dense-cache decode and paged-cache decode produce BITWISE identical
    logits at every step (greedy), pool pages deliberately non-contiguous."""
    cfg, params = toy_model
    rope = make_rope_cache(cfg)
    b, S, page = 2, 32, 8
    maxp = S // page
    L = cfg.model.num_layers
    nkv, d = cfg.model.num_attention_heads_kv, cfg.model.kv_channels
    tokens = np.random.RandomState(0).randint(2, VOCAB, (b, S)).astype(np.int32)
    prompt_len = 7

    caches = init_kv_caches(cfg, b, S, _compute_dtype(cfg))
    logits_d, caches = model_forward(
        cfg, params, jnp.asarray(tokens[:, :prompt_len]),
        position_ids=jnp.arange(prompt_len)[None, :].repeat(b, 0),
        rope_cache=rope, kv_caches=caches, cache_index=jnp.int32(0))

    # interleave the two rows' pages so the block tables are non-trivial
    P = 1 + b * maxp
    pool_k = jnp.zeros((L, P, page, nkv, d), jnp.float32)
    pool_v = jnp.zeros((L, P, page, nkv, d), jnp.float32)
    bt = np.asarray([[1 + 2 * j for j in range(maxp)],
                     [2 + 2 * j for j in range(maxp)]], np.int32)
    ck, cv = caches
    pool_k = pool_k.at[:, bt.reshape(-1)].set(
        ck.reshape(L, b, maxp, page, nkv, d).reshape(L, -1, page, nkv, d))
    pool_v = pool_v.at[:, bt.reshape(-1)].set(
        cv.reshape(L, b, maxp, page, nkv, d).reshape(L, -1, page, nkv, d))
    bt = jnp.asarray(bt)

    tok = jnp.argmax(logits_d[:, -1, :VOCAB], -1).astype(jnp.int32)
    pos = prompt_len
    for _ in range(12):
        ld, caches = model_forward(
            cfg, params, tok[:, None],
            position_ids=jnp.full((b, 1), pos, jnp.int32),
            rope_cache=rope, kv_caches=caches, cache_index=jnp.int32(pos))
        lp, (pool_k, pool_v) = model_forward(
            cfg, params, tok[:, None],
            position_ids=jnp.full((b, 1), pos, jnp.int32),
            rope_cache=rope, kv_caches=(pool_k, pool_v),
            paged=PagedState(bt, jnp.full((b,), pos, jnp.int32)))
        assert bool(jnp.all(ld == lp)), f"logits diverged at position {pos}"
        tok = jnp.argmax(ld[:, -1, :VOCAB], -1).astype(jnp.int32)
        pos += 1


# ---------------------------------------------------------------------------
# Engine level
# ---------------------------------------------------------------------------


def test_engine_greedy_matches_generate_tokens(toy_model):
    """Engine greedy decode == the sequential dense generate_tokens path."""
    cfg, params = toy_model
    eng = ContinuousBatchingEngine(cfg, params, ToyTokenizer(),
                                   max_slots=4, max_seq=128)
    prompt = [2 + i % 60 for i in range(10)]
    req = eng.submit(prompt, 8, top_k=1, termination_id=10 ** 9)
    eng.run_until_idle()
    toks, _ = req.result(timeout=5)

    S = 64
    tokens = np.zeros((1, S), np.int32)
    tokens[0, :10] = prompt
    res = generate_tokens(
        cfg, params, tokens, np.array([10], np.int32), 18,
        prefill_len=8, termination_id=10 ** 9,
        sample_key=jax.random.PRNGKey(0), top_k=1)
    np.testing.assert_array_equal(
        np.asarray(toks[10:]), np.asarray(res.tokens)[0, 10:18])


def test_engine_logprobs_match_dense_score(toy_model):
    """Engine per-token log-probs == teacher-forced rescoring of the final
    sequence (the dense path's own consistency contract)."""
    from megatron_llm_tpu.generation.generation import score_tokens

    cfg, params = toy_model
    eng = ContinuousBatchingEngine(cfg, params, ToyTokenizer(),
                                   max_slots=2, max_seq=128)
    prompt = [3, 4, 5, 6, 7, 8]
    req = eng.submit(prompt, 10, top_k=1, termination_id=10 ** 9,
                     return_log_probs=True)
    eng.run_until_idle()
    toks, gen_lp = req.result(timeout=5)
    full = np.asarray(toks, np.int32)[None, :]
    lp_score = np.asarray(score_tokens(cfg, params, jnp.asarray(full)))[0]
    lp_engine = np.asarray(req.prompt_log_probs + gen_lp)
    np.testing.assert_allclose(lp_engine, lp_score[: len(lp_engine)],
                               atol=2e-4, rtol=2e-4)


def test_engine_sampling_slot_invariant(toy_model):
    """A seeded sampled request generates the SAME tokens whether it runs
    alone or alongside other requests in different slots — per-slot keys are
    (seed, step) functions, not (slot, tick)."""
    cfg, params = toy_model
    prompt = [5, 9, 13, 17]
    kw = dict(temperature=0.8, top_p=0.9, seed=123, termination_id=10 ** 9)

    eng1 = ContinuousBatchingEngine(cfg, params, ToyTokenizer(),
                                    max_slots=1, max_seq=128)
    r1 = eng1.submit(prompt, 12, **kw)
    eng1.run_until_idle()

    eng2 = ContinuousBatchingEngine(cfg, params, ToyTokenizer(),
                                    max_slots=4, max_seq=128)
    # fill other slots with competing greedy traffic first so the seeded
    # request lands in a later slot
    others = [eng2.submit([7 + i] * 3, 15, top_k=1, termination_id=10 ** 9)
              for i in range(3)]
    r2 = eng2.submit(prompt, 12, **kw)
    eng2.run_until_idle()
    for o in others:
        o.result(timeout=5)

    t1, _ = r1.result(timeout=5)
    t2, _ = r2.result(timeout=5)
    assert t1 == t2


def test_engine_early_termination_and_page_return(toy_model):
    """Termination id stops a row early; its pages return to the pool while
    other rows keep decoding."""
    cfg, params = toy_model
    eng = ContinuousBatchingEngine(cfg, params, ToyTokenizer(),
                                   max_slots=2, max_seq=128)
    # find the first greedy token, then use it as the termination id
    probe = eng.submit([3, 3, 3, 3], 1, top_k=1, termination_id=10 ** 9)
    eng.run_until_idle()
    first_tok = probe.result(timeout=5)[0][-1]

    short = eng.submit([3, 3, 3, 3], 50, top_k=1, termination_id=first_tok)
    long_ = eng.submit([9, 9, 9, 9], 30, top_k=1, termination_id=10 ** 9)
    eng.run_until_idle()
    t_short, _ = short.result(timeout=5)
    t_long, _ = long_.result(timeout=5)
    assert len(t_short) == 5  # stopped on the first generated token
    assert len(t_long) == 34  # ran to its budget
    # all refs returned; retired prompts may stay cached-idle for reuse
    assert int(eng.pool.refcounts.sum()) == 0
    assert (eng.pool.num_free + len(eng.pool.cached)
            == eng.pool.num_pages - 1)


def test_block_table_alloc_free_stress(toy_model):
    """Churn a deliberately tiny pool: requests queue behind page pressure,
    pages are never double-booked across active slots, and the pool is whole
    when the queue drains."""
    cfg, params = toy_model
    eng = ContinuousBatchingEngine(cfg, params, ToyTokenizer(),
                                   max_slots=3, page_size=16, num_pages=13,
                                   max_seq=128)
    rng = np.random.default_rng(0)
    reqs = []
    for i in range(17):
        plen = int(rng.integers(1, 40))
        gen_len = int(rng.integers(1, 30))
        reqs.append(eng.submit([2 + int(x) for x in rng.integers(0, 60, plen)],
                               gen_len, top_k=1, termination_id=10 ** 9))

    total = eng.pool.num_pages - 1
    steps = 0
    while True:
        n = eng.step()
        steps += 1
        held = [p for r in eng._slots if r is not None for p in r._pages]
        assert all(p != 0 for p in held), "null page allocated"
        # refcount-exact accounting (the PR-5 three-state page model):
        # every page is free XOR referenced XOR cached-idle, and refcounts
        # equal the number of block tables holding the page
        from collections import Counter

        holders = Counter(held)
        free = set(eng.pool._free)
        for p in range(1, eng.pool.num_pages):
            assert eng.pool.refcounts[p] == holders.get(p, 0), \
                f"page {p} refcount drift"
            if p in free:
                assert eng.pool.refcounts[p] == 0 and p not in eng.pool.cached
        cached_idle = sum(1 for p in eng.pool.cached
                          if eng.pool.refcounts[p] == 0)
        distinct_held = len(holders)
        assert distinct_held + eng.pool.num_free + cached_idle == total, \
            "pages leaked"
        if n == 0 and not eng._queue:
            break
        assert steps < 5000
    for r in reqs:
        toks, _ = r.result(timeout=5)
        assert len(toks) == len(r.prompt) + len(r.generated)
        assert 1 <= len(r.generated) <= r.max_new_tokens
    # drained: nothing referenced; pages are either free or cached-idle
    # (reusable by the next prompt, reclaimable under pressure)
    assert int(eng.pool.refcounts.sum()) == 0
    assert eng.pool.num_free + len(eng.pool.cached) == total


def test_engine_rejects_oversized_request(toy_model):
    cfg, params = toy_model
    eng = ContinuousBatchingEngine(cfg, params, ToyTokenizer(),
                                   max_slots=2, max_seq=64)
    with pytest.raises(ValueError, match="longer than allowed"):
        eng.submit(list(range(2, 60)), 32)


def test_engine_concurrent_submitters_share_ticks(toy_model):
    """Requests submitted from many threads share decode ticks: total ticks
    is far below the serialized tick count (the >= 3x batching claim the
    decode bench quantifies)."""
    cfg, params = toy_model
    eng = ContinuousBatchingEngine(cfg, params, ToyTokenizer(),
                                   max_slots=8, max_seq=128)
    reqs = [None] * 8

    def submit(i):
        reqs[i] = eng.submit([2 + i, 3 + i, 4 + i], 12, top_k=1,
                             termination_id=10 ** 9)

    threads = [threading.Thread(target=submit, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    eng.run_until_idle()
    total_generated = 0
    for r in reqs:
        toks, _ = r.result(timeout=5)
        total_generated += len(r.generated)
    assert total_generated == 8 * 12
    # serialized decoding would need one tick per generated token
    assert eng.ticks <= 2 * 12 < total_generated


# ---------------------------------------------------------------------------
# Per-slot sampler
# ---------------------------------------------------------------------------


def test_sample_per_slot_matches_static_filters():
    """Row-wise dynamic top-k/top-p filtering == the static single-config
    filters sample() uses, and greedy rows == sample()'s greedy branch."""
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(4, 32)) * 3, jnp.float32)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(4))
    top_k = jnp.asarray([1, 5, 0, 0], jnp.int32)
    top_p = jnp.asarray([0.0, 0.0, 0.7, 0.0], jnp.float32)
    temp = jnp.ones((4,), jnp.float32)

    out = sample_per_slot(keys, logits, top_k=top_k, top_p=top_p,
                          temperature=temp)
    # row 0 greedy == sample() greedy
    assert int(out[0]) == int(sample(None, logits[:1], top_k=1)[0])
    # row 1: token must survive the static top-5 filter
    filt_k = modify_logits_for_top_k_filtering(logits[1:2], 5)
    assert float(filt_k[0, int(out[1])]) > -1e9
    # row 2: token must survive the static top-p filter
    filt_p = modify_logits_for_top_p_filtering(logits[2:3], 0.7)
    assert float(filt_p[0, int(out[2])]) > -1e9
    # per-row keys: same row inputs + same key -> same sample regardless of
    # the rest of the batch
    solo = sample_per_slot(keys[1:2], logits[1:2], top_k=top_k[1:2],
                           top_p=top_p[1:2], temperature=temp[1:2])
    assert int(solo[0]) == int(out[1])


def test_sample_per_slot_temperature_is_ignored_for_greedy():
    logits = jnp.asarray([[0.1, 0.9, 0.5]])
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(1))
    out = sample_per_slot(keys, logits,
                          top_k=jnp.asarray([1]), top_p=jnp.asarray([0.0]),
                          temperature=jnp.asarray([0.01]))
    assert int(out[0]) == 1


def test_sample_per_slot_temperature_to_zero_approaches_greedy():
    """temperature -> 0 collapses the categorical onto the argmax: every
    sampled row must equal the greedy pick whatever its key (the edge the
    speculative verify's acceptance distributions inherit)."""
    rng = np.random.default_rng(3)
    logits = jnp.asarray(rng.normal(size=(6, 32)) * 2, jnp.float32)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(6))
    out = sample_per_slot(
        keys, logits, top_k=jnp.zeros((6,), jnp.int32),
        top_p=jnp.zeros((6,), jnp.float32),
        temperature=jnp.full((6,), 1e-4, jnp.float32))
    assert np.array_equal(np.asarray(out),
                          np.asarray(jnp.argmax(logits, axis=-1)))


def test_sample_per_slot_top_k_1_is_exact_argmax():
    """top_k=1 rows are EXACTLY argmax over the vocab-masked logits — no
    key dependence, no temperature, padding never wins.  Greedy
    speculative acceptance compares against this value bitwise."""
    rng = np.random.default_rng(4)
    logits = np.asarray(rng.normal(size=(4, 32)) * 2, np.float32)
    logits[:, 30:] = 50.0  # padding region would win without the mask
    logits = jnp.asarray(logits)
    outs = []
    for seed in (0, 7):
        keys = jax.vmap(jax.random.PRNGKey)(seed + jnp.arange(4))
        outs.append(np.asarray(sample_per_slot(
            keys, logits, top_k=jnp.ones((4,), jnp.int32),
            top_p=jnp.zeros((4,), jnp.float32),
            temperature=jnp.asarray([1.0, 0.2, 5.0, 1.0]),
            vocab_size=30)))
    assert np.array_equal(outs[0], outs[1])  # keys are irrelevant
    assert np.all(outs[0] < 30)              # padding masked
    masked = jnp.where(jnp.arange(32)[None, :] >= 30, -1e10, logits)
    assert np.array_equal(outs[0], np.asarray(jnp.argmax(masked, axis=-1)))


def test_sample_per_slot_per_row_key_independence_under_fold_in():
    """The engine derives row keys as fold_in(request_key, step): rows
    sharing LOGITS but folded with different data must draw independently,
    the same (key, data) pair must redraw identically wherever the row
    sits, and reusing a consumed key reproduces the draw — the reuse
    hazard the speculative verify avoids with disjoint fold_in streams
    (graftcheck rng-key-reuse)."""
    rng = np.random.default_rng(5)
    row = rng.normal(size=(1, 64)).astype(np.float32)
    logits = jnp.asarray(np.repeat(row, 8, axis=0))
    base = jax.random.PRNGKey(42)
    keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(jnp.arange(8))
    kw = dict(top_k=jnp.zeros((8,), jnp.int32),
              top_p=jnp.zeros((8,), jnp.float32),
              temperature=jnp.full((8,), 1.5, jnp.float32))
    out = np.asarray(sample_per_slot(keys, logits, **kw))
    # identical logits, distinct fold_in data -> not one collapsed draw
    assert len(set(out.tolist())) > 1
    # same fold_in data in a different slot -> identical draw
    perm = jnp.asarray([3, 0, 6, 1, 7, 2, 5, 4])
    out_p = np.asarray(sample_per_slot(
        keys[perm], logits, **kw))
    assert np.array_equal(out_p, out[np.asarray(perm)])
    # a REUSED key replays its draw exactly (why streams must be disjoint)
    twice = jnp.concatenate([keys[:1], keys[:1]], axis=0)
    out_r = np.asarray(sample_per_slot(
        twice, logits[:2], top_k=kw["top_k"][:2], top_p=kw["top_p"][:2],
        temperature=kw["temperature"][:2]))
    assert out_r[0] == out_r[1]


def test_filtered_logits_per_slot_is_the_sampler_distribution():
    """softmax(filtered_logits_per_slot(...)) IS the categorical the
    sampler draws from: drawing from the returned logits with the same
    keys reproduces sample_per_slot exactly.  The speculative rejection
    sampler's p and q hang on this equivalence."""
    from megatron_llm_tpu.generation.sampling import filtered_logits_per_slot

    rng = np.random.default_rng(6)
    logits = jnp.asarray(rng.normal(size=(5, 40)) * 3, jnp.float32)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(5))
    top_k = jnp.asarray([1, 4, 0, 0, 2], jnp.int32)
    top_p = jnp.asarray([0.0, 0.0, 0.8, 0.0, 0.0], jnp.float32)
    temp = jnp.asarray([1.0, 0.7, 1.3, 2.0, 1.0], jnp.float32)
    filtered, greedy = filtered_logits_per_slot(
        logits, top_k=top_k, top_p=top_p, temperature=temp, vocab_size=38)
    manual = jnp.where(
        top_k == 1, greedy,
        jax.vmap(lambda k, r: jax.random.categorical(k, r))(keys, filtered))
    out = sample_per_slot(keys, logits, top_k=top_k, top_p=top_p,
                          temperature=temp, vocab_size=38)
    assert np.array_equal(np.asarray(manual), np.asarray(out))


# ---------------------------------------------------------------------------
# cached_jit regression (satellite: id(cfg) keying)
# ---------------------------------------------------------------------------


def test_cached_jit_keys_on_config_content():
    """Two configs with EQUAL contents share one compiled entry (no id
    dependence — the id-recycling hazard of the old key); different contents
    get different entries."""
    clear_jit_cache()
    def mk(hidden_size=32):
        return make_config(
            "llama2", num_layers=1, hidden_size=hidden_size,
            num_attention_heads=2, num_attention_heads_kv=2,
            ffn_hidden_size=64, seq_length=64,
            max_position_embeddings=64, vocab_size=VOCAB)
    cfg_a, cfg_b = mk(), mk()
    assert cfg_a is not cfg_b
    assert config_fingerprint(cfg_a) == config_fingerprint(cfg_b)

    calls = []
    fn_a = cached_jit(cfg_a, "t", (1,), lambda: calls.append(1) or (lambda x: x))
    fn_b = cached_jit(cfg_b, "t", (1,), lambda: calls.append(1) or (lambda x: x))
    assert fn_a is fn_b and len(calls) == 1, "equal configs must share"

    cfg_c = mk(hidden_size=64)
    assert config_fingerprint(cfg_c) != config_fingerprint(cfg_a)
    fn_c = cached_jit(cfg_c, "t", (1,), lambda: calls.append(1) or (lambda x: x))
    assert fn_c is not fn_a and len(calls) == 2

    # GC'd configs cannot alias: the key survives the object, by value
    key_count = len(_JIT_CACHE)
    del cfg_a, cfg_b
    import gc

    gc.collect()
    cfg_d = mk()
    fn_d = cached_jit(cfg_d, "t", (1,), lambda: calls.append(1) or (lambda x: x))
    assert fn_d is fn_b and len(calls) == 2 and len(_JIT_CACHE) == key_count
    clear_jit_cache()
