"""Checkpoint resharding tool tests (reference analog: the TP=2/PP=2
shard-and-back step of tests/test_llama_weights.py:180-189)."""

import json
import os
import sys
from pathlib import Path

import jax
import numpy as np
import orbax.checkpoint as ocp
import pytest

REPO = Path(__file__).parent.parent
sys.path.insert(0, str(REPO / "tools"))

from checkpoint_util import reshard_checkpoint  # noqa: E402

from megatron_llm_tpu.config import Config, apply_architecture  # noqa: E402
from megatron_llm_tpu.checkpointing import save_checkpoint  # noqa: E402
from megatron_llm_tpu.models.language_model import (  # noqa: E402
    init_model_params,
    padded_vocab_size,
)


def tiny_cfg(tp=1):
    cfg = Config()
    apply_architecture(cfg, "llama2")
    cfg.model.num_layers = 2
    cfg.model.hidden_size = 64
    cfg.model.num_attention_heads = 8
    cfg.model.num_attention_heads_kv = 8
    cfg.model.vocab_size = 500
    cfg.model.make_vocab_size_divisible_by = 128
    cfg.model.max_position_embeddings = 64
    cfg.parallel.tensor_model_parallel_size = tp
    cfg.training.params_dtype = "float32"
    cfg.finalize(n_devices=None)
    return cfg


def test_reshard_repads_vocab_and_updates_meta(tmp_path):
    cfg = tiny_cfg(tp=1)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    src_rows = padded_vocab_size(500, cfg)  # 512 at tp=1
    assert params["embedding"]["word_embeddings"].shape[0] == src_rows

    save_checkpoint(cfg, str(tmp_path / "src"), 7, params, consumed_samples=3)
    meta = reshard_checkpoint(str(tmp_path / "src"), str(tmp_path / "dst"),
                              target_tp=8, target_pp=2)
    assert meta["config"]["parallel"]["tensor_model_parallel_size"] == 8
    assert meta["config"]["parallel"]["pipeline_model_parallel_size"] == 2

    restored = ocp.StandardCheckpointer().restore(
        str(tmp_path / "dst" / "iter_0000007" / "params"))
    emb = np.asarray(restored["embedding"]["word_embeddings"])
    assert emb.shape[0] == 1024  # 128 * 8 = 1024-multiple at tp=8
    np.testing.assert_array_equal(
        emb[:src_rows], np.asarray(params["embedding"]["word_embeddings"]))
    np.testing.assert_array_equal(emb[src_rows:], 0.0)
    head = np.asarray(restored["lm_head"]["kernel"])
    assert head.shape[1] == 1024
    # tracker carries the iteration forward
    assert (tmp_path / "dst" / "latest_checkpointed_iteration.txt").read_text() == "7"


def test_reshard_back_roundtrip(tmp_path):
    cfg = tiny_cfg(tp=1)
    params = init_model_params(cfg, jax.random.PRNGKey(1))
    save_checkpoint(cfg, str(tmp_path / "a"), 1, params)
    reshard_checkpoint(str(tmp_path / "a"), str(tmp_path / "b"),
                       target_tp=8, target_pp=1)
    reshard_checkpoint(str(tmp_path / "b"), str(tmp_path / "c"),
                       target_tp=1, target_pp=1)
    restored = ocp.StandardCheckpointer().restore(
        str(tmp_path / "c" / "iter_0000001" / "params"))
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        restored, jax.tree.map(np.asarray, params))


def test_reshard_rejects_bad_layout(tmp_path):
    cfg = tiny_cfg(tp=1)
    params = init_model_params(cfg, jax.random.PRNGKey(2))
    save_checkpoint(cfg, str(tmp_path / "src"), 1, params)
    with pytest.raises(ValueError, match="not divisible"):
        reshard_checkpoint(str(tmp_path / "src"), str(tmp_path / "dst"),
                           target_tp=1, target_pp=3)
    with pytest.raises(ValueError, match="cannot be sharded"):
        reshard_checkpoint(str(tmp_path / "src"), str(tmp_path / "dst2"),
                           target_tp=16, target_pp=1)
