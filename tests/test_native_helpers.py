"""Native C++ index helpers vs the numpy fallbacks — exact parity
(reference analog: helpers.cpp is the only implementation there; here both
paths must agree bit-for-bit)."""

import numpy as np
import pytest

from megatron_llm_tpu.data import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native helpers not built (no g++?)")


def numpy_sample_idx(sizes, doc_idx, seq_length, num_samples):
    doc_lens = sizes[doc_idx].astype(np.int64)
    cum = np.concatenate(([0], np.cumsum(doc_lens)))
    starts = np.arange(num_samples + 1, dtype=np.int64) * seq_length
    assert starts[-1] <= cum[-1] - 1
    doc_of_start = np.searchsorted(cum, starts, side="right") - 1
    out = np.empty((num_samples + 1, 2), np.int32)
    out[:, 0] = doc_of_start
    out[:, 1] = starts - cum[doc_of_start]
    return out


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("seq_length", [7, 32, 129])
@pytest.mark.parametrize("min_doc_len", [0, 1])  # 0 → zero-length docs present
def test_sample_idx_parity(seed, seq_length, min_doc_len):
    rng = np.random.RandomState(seed)
    sizes = rng.randint(min_doc_len, 200, size=100).astype(np.int32)
    doc_idx = rng.permutation(np.tile(np.arange(100, dtype=np.int32), 3))
    total = int(sizes[doc_idx].sum())
    num_samples = (total - 1) // seq_length
    ours = native.build_sample_idx(sizes, doc_idx, seq_length, num_samples)
    ref = numpy_sample_idx(sizes, doc_idx, seq_length, num_samples)
    np.testing.assert_array_equal(ours, ref)


def test_sample_idx_boundary_on_empty_doc_run():
    # boundary lands exactly where a run of empty docs sits: both paths must
    # point past the empties at the next non-empty document
    sizes = np.array([5, 0, 0, 4, 7], np.int32)
    doc_idx = np.arange(5, dtype=np.int32)
    ours = native.build_sample_idx(sizes, doc_idx, 5, 2)
    ref = numpy_sample_idx(sizes, doc_idx, 5, 2)
    np.testing.assert_array_equal(ours, ref)
    assert ours[1].tolist() == [3, 0]  # skipped docs 1, 2


def test_sample_idx_exhaustion_raises():
    sizes = np.array([10], np.int32)
    doc_idx = np.array([0], np.int32)
    with pytest.raises(AssertionError):
        native.build_sample_idx(sizes, doc_idx, 8, 5)


def test_doc_boundary_alignment():
    # boundaries exactly at document edges
    sizes = np.array([8, 8, 8], np.int32)
    doc_idx = np.array([0, 1, 2], np.int32)
    out = native.build_sample_idx(sizes, doc_idx, 8, 2)
    np.testing.assert_array_equal(out, [[0, 0], [1, 0], [2, 0]])


@pytest.mark.parametrize("weights", [[0.5, 0.5], [0.7, 0.2, 0.1],
                                     [0.05, 0.95], [1.0]])
def test_blending_parity(weights):
    w = np.asarray(weights, np.float64)
    size = 997
    di, dsi = native.build_blending_indices(w, size)
    # python-loop reference (the pre-native fallback in blendable_dataset)
    n = len(w)
    current = np.zeros(n, np.int64)
    for i in range(size):
        k = int(np.argmax(w * (i + 1) - current))
        assert di[i] == k
        assert dsi[i] == current[k]
        current[k] += 1
    # proportionality: each dataset consumed ~weight*size
    counts = np.bincount(di, minlength=n)
    np.testing.assert_allclose(counts / size, w, atol=2 / size)


def test_blendable_dataset_uses_native():
    from megatron_llm_tpu.data import blendable_dataset

    class Fake:
        def __init__(self, tag, n):
            self.tag, self.n = tag, n

        def __len__(self):
            return self.n

        def __getitem__(self, i):
            return (self.tag, i)

    ds = blendable_dataset.BlendableDataset(
        [Fake("a", 10), Fake("b", 10)], [0.3, 0.7], 50)
    tags = [ds[i][0] for i in range(50)]
    assert 10 <= tags.count("a") <= 20
    # the dispatch really took the native path: its output must be the
    # native result verbatim (not the python-loop fallback's recomputation)
    di, dsi = native.build_blending_indices(ds.weights, 50)
    np.testing.assert_array_equal(ds.dataset_index, di)
    np.testing.assert_array_equal(ds.dataset_sample_index, dsi)
    assert ds.dataset_index.dtype == np.uint8
