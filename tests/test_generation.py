"""Generation stack tests (reference analog: none directly — the reference's
text_generation has no unit tests; we gate on internal consistency instead:
greedy decode must match teacher-forced argmax, KV-cached decode must match
full-context forward, and sampling filters must match their definitions)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.generation import InferenceEngine
from megatron_llm_tpu.generation.generation import generate_tokens, score_tokens
from megatron_llm_tpu.generation.sampling import (
    NEG_INF,
    modify_logits_for_top_k_filtering,
    modify_logits_for_top_p_filtering,
    sample,
)
from megatron_llm_tpu.models import init_model_params, make_config


VOCAB = 67  # deliberately not a multiple of the padding divisor


class ToyTokenizer:
    """Deterministic char-level tokenizer for engine tests."""

    eod = 0
    bos = 1

    @property
    def vocab_size(self):
        return VOCAB

    def tokenize(self, text):
        return [2 + (ord(c) % (VOCAB - 2)) for c in text]

    def detokenize(self, ids):
        return "".join(chr(97 + (i % 26)) for i in ids if i >= 2)


@pytest.fixture(scope="module")
def toy_model():
    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, ffn_hidden_size=128, seq_length=128,
        max_position_embeddings=256, vocab_size=VOCAB,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype="float32", use_flash_attn=False,
    )
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def test_greedy_matches_teacher_forced_rescoring(toy_model):
    """Greedy-decode tokens, then score the same sequence teacher-forced: at
    every generated position the argmax of the scoring distribution must be
    the generated token (KV-cached decode == full-context forward)."""
    cfg, params = toy_model
    b, prompt_len, S = 2, 8, 24
    tokens = np.random.RandomState(0).randint(2, VOCAB, size=(b, S)).astype(np.int32)
    lengths = np.array([prompt_len, prompt_len - 3], np.int32)

    res = generate_tokens(
        cfg, params, tokens, lengths, S,
        prefill_len=4, termination_id=VOCAB + 99,  # unreachable -> no early stop
        sample_key=jax.random.PRNGKey(1), top_k=1,
    )
    out = np.asarray(res.tokens)
    # direct check: rerun a full (non-cached) forward; each generated token
    # must equal the argmax continuation over the real (unpadded) vocab
    from megatron_llm_tpu.models.language_model import model_forward

    logits, _ = model_forward(cfg, params, jnp.asarray(out))
    greedy = np.asarray(jnp.argmax(logits[:, :-1, :VOCAB], -1))
    for row in range(b):
        for pos in range(int(lengths[row]), S):
            assert out[row, pos] == greedy[row, pos - 1], (row, pos)


def test_generated_log_probs_match_score(toy_model):
    """output_log_probs from the decode loop == teacher-forced score of the
    final sequence (generation.py:227-239 indexing contract)."""
    cfg, params = toy_model
    b, S = 2, 16
    tokens = np.random.RandomState(1).randint(2, VOCAB, size=(b, S)).astype(np.int32)
    lengths = np.array([6, 5], np.int32)
    res = generate_tokens(
        cfg, params, tokens, lengths, S,
        prefill_len=2, termination_id=VOCAB + 99,
        sample_key=jax.random.PRNGKey(2), top_k=1,
    )
    lp_loop = np.asarray(res.output_log_probs)
    lp_score = np.asarray(score_tokens(cfg, params, res.tokens))
    np.testing.assert_allclose(lp_loop, lp_score, atol=2e-4, rtol=2e-4)


def test_early_termination(toy_model):
    """Once every row emits the termination id, the loop stops and lengths
    record prompt+generated (generation.py:253-269)."""
    cfg, params = toy_model
    b, S = 2, 32
    tokens = np.full((b, S), 3, np.int32)
    lengths = np.array([4, 4], np.int32)
    # termination_id = the greedy token the model emits first: force instant stop
    res0 = generate_tokens(
        cfg, params, tokens, lengths, S, prefill_len=4,
        termination_id=VOCAB + 99, sample_key=jax.random.PRNGKey(0), top_k=1,
    )
    first_tok = int(np.asarray(res0.tokens)[0, 4])
    res = generate_tokens(
        cfg, params, tokens, lengths, S, prefill_len=4,
        termination_id=first_tok, sample_key=jax.random.PRNGKey(0), top_k=1,
    )
    lens = np.asarray(res.lengths)
    assert lens.max() < S  # early stop actually happened


def test_prefill_bucketing_invariance(toy_model):
    """Bucketing the prefill down is numerically invisible: teacher-forced
    positions between prefill and prompt end give identical generations."""
    cfg, params = toy_model
    b, S = 1, 24
    tokens = np.random.RandomState(3).randint(2, VOCAB, size=(b, S)).astype(np.int32)
    lengths = np.array([10], np.int32)
    outs = []
    for prefill in (1, 4, 8):
        res = generate_tokens(
            cfg, params, tokens, lengths, S, prefill_len=prefill,
            termination_id=VOCAB + 99, sample_key=jax.random.PRNGKey(5), top_k=1,
        )
        outs.append(np.asarray(res.tokens))
    np.testing.assert_array_equal(outs[0], outs[1])
    np.testing.assert_array_equal(outs[0], outs[2])


def test_top_k_filter():
    logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0, 4.0]])
    out = np.asarray(modify_logits_for_top_k_filtering(logits, 2))
    assert (out[0, [1, 4]] > NEG_INF / 2).all()
    assert (out[0, [0, 2, 3]] <= NEG_INF / 2).all()


def test_top_p_filter():
    # probs ~ [0.645, 0.237, 0.087, 0.032]: top_p=0.7 keeps the first token
    # plus the boundary-crossing one (the reference's shift-by-one)
    logits = jnp.log(jnp.asarray([[0.645, 0.237, 0.087, 0.032]]))
    out = np.asarray(modify_logits_for_top_p_filtering(logits, 0.7))
    assert out[0, 0] > NEG_INF / 2
    assert out[0, 1] > NEG_INF / 2
    assert (out[0, 2:] <= NEG_INF / 2).all()


def test_sample_greedy_and_clamp():
    logits = jnp.asarray([[0.1, 0.9, 0.5]])
    assert int(sample(None, logits, top_k=1)[0]) == 1
    # vocab padding clamp: argmax in padded region clamps into [0, vocab)
    logits = jnp.asarray([[0.1, 0.2, 9.0]])
    assert int(sample(None, logits, top_k=1, vocab_size=2)[0]) == 1


def test_engine_generate_and_post_process(toy_model):
    cfg, params = toy_model
    engine = InferenceEngine(cfg, params, ToyTokenizer())
    texts, segments, log_probs, tokens = engine.generate_and_post_process(
        ["hello world", "hi"], tokens_to_generate=6,
        return_output_log_probs=True, top_k_sampling=1,
    )
    assert len(texts) == 2 and len(segments) == 2
    assert all(isinstance(t, str) for t in texts)
    assert len(log_probs[0]) == len(segments[0]) - 1
    # prompt is preserved verbatim at the head of the generation
    tok = ToyTokenizer()
    assert tokens[0][: len(tok.tokenize("hello world"))] == tok.tokenize("hello world")


def test_engine_scoring_mode(toy_model):
    """tokens_to_generate=0 -> scoring (api.py:129-131)."""
    cfg, params = toy_model
    engine = InferenceEngine(cfg, params, ToyTokenizer())
    tokens, lengths, log_probs = engine.generate(
        ["scoring prompt"], tokens_to_generate=0)
    assert log_probs.shape == (1, tokens.shape[1] - 1)


def test_beam_search(toy_model):
    """Beam-1 greedy == greedy decode; beam-4 returns descending scores."""
    cfg, params = toy_model
    b, S = 1, 20
    tokens = np.random.RandomState(7).randint(2, VOCAB, size=(b, S)).astype(np.int32)
    lengths = np.array([8], np.int32)

    from megatron_llm_tpu.generation.generation import beam_search

    out1, scores1 = beam_search(
        cfg, params, tokens, 8, beam_size=1, stop_token=VOCAB + 99)
    greedy = generate_tokens(
        cfg, params, tokens, lengths, S, prefill_len=8,
        termination_id=VOCAB + 99, sample_key=jax.random.PRNGKey(0), top_k=1,
    )
    np.testing.assert_array_equal(np.asarray(out1)[0], np.asarray(greedy.tokens)[0])

    out4, scores4 = beam_search(
        cfg, params, tokens, 8, beam_size=4, stop_token=VOCAB + 99,
        num_return_gen=4)
    s = np.asarray(scores4)
    assert (np.diff(s) <= 1e-6).all()  # sorted best-first
    # the best beam is at least as good as greedy's sum log-prob
    lp_greedy = np.asarray(
        score_tokens(cfg, params, greedy.tokens))[0, 7:].sum()
    assert s[0] >= lp_greedy / (S - 8) ** 1.0 - 1e-4
