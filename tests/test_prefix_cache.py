"""Prefix-cached paged KV + chunked prefill tests (ISSUE 5).

Gates: (1) generation is BITWISE identical (tokens and log-probs, jnp
fallback) with the prefix cache on vs off, and with chunked vs monolithic
prefill — sharing pages and splitting prompts must be pure optimizations;
(2) page refcounts are exact under alloc/share/release/evict churn: no
page is ever simultaneously free and referenced, copy-on-write never
mutates a shared page, and the pool drains whole; (3) admission under page
pressure evicts cached-idle pages (LRU, leaf-first) instead of rejecting
while reusable pages sit idle; (4) prefill chunks interleave with decode
ticks instead of stalling active slots.
"""

import numpy as np
import pytest

import jax

from megatron_llm_tpu.generation import (
    ContinuousBatchingEngine,
    EngineOverloaded,
)
from megatron_llm_tpu.generation.engine import (
    NULL_PAGE,
    PagedKVPool,
    PrefixCache,
)
from megatron_llm_tpu.models import init_model_params, make_config

VOCAB = 67


class ToyTokenizer:
    eod = 0
    bos = 1
    vocab_size = VOCAB

    def tokenize(self, text):
        return [2 + (ord(c) % (VOCAB - 2)) for c in text]

    def detokenize(self, ids):
        return "".join(chr(97 + (i % 26)) for i in ids if i >= 2)


@pytest.fixture(scope="module")
def toy_model():
    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, ffn_hidden_size=128, seq_length=128,
        max_position_embeddings=256, vocab_size=VOCAB,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype="float32", use_flash_attn=False,
    )
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _engine(cfg, params, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_seq", 128)
    return ContinuousBatchingEngine(cfg, params, ToyTokenizer(), **kw)


def _run(eng, jobs):
    """Submit (prompt, max_new, kwargs) jobs sequentially-admitted but
    batch-decoded; returns [(tokens, gen_log_probs, prompt_log_probs)]."""
    reqs = [eng.submit(p, n, **kw) for p, n, kw in jobs]
    eng.run_until_idle()
    out = []
    for r in reqs:
        toks, lps = r.result(timeout=30)
        out.append((toks, lps, r.prompt_log_probs))
    return out


SHARED = [2 + (i * 7) % 60 for i in range(48)]  # 3 full pages @ page 16


# ---------------------------------------------------------------------------
# Bitwise parity
# ---------------------------------------------------------------------------


def test_bitwise_parity_cache_on_vs_off(toy_model):
    """Same traffic through cache-on and cache-off engines: identical
    tokens AND log-probs (exact float equality — shared pages must hold
    bitwise the KV a cold prefill would compute)."""
    cfg, params = toy_model
    jobs = []
    for i in range(6):
        tail = [3 + (i * 11 + j) % 60 for j in range(5 + 3 * i)]
        jobs.append((SHARED + tail, 10,
                     dict(top_k=1, termination_id=10 ** 9)))
    # one page-aligned full duplicate (the COW path) and one sampled row
    jobs.append((list(SHARED), 8, dict(top_k=1, termination_id=10 ** 9)))
    jobs.append((list(SHARED), 8, dict(top_k=1, termination_id=10 ** 9)))
    jobs.append((SHARED + [5, 6], 8,
                 dict(temperature=0.8, top_p=0.9, seed=7,
                      termination_id=10 ** 9)))

    # submit one-by-one so later requests can hit what earlier ones cached
    on = _engine(cfg, params, prefix_cache=True)
    res_on = []
    for j in jobs:
        res_on.extend(_run(on, [j]))
    off = _engine(cfg, params, prefix_cache=False)
    res_off = []
    for j in jobs:
        res_off.extend(_run(off, [j]))

    for (t1, lp1, _), (t2, lp2, _) in zip(res_on, res_off):
        assert t1 == t2
        assert lp1 == lp2  # exact: same bits through the same tick program
    assert on.prefix_hit_tokens > 0, "shared prefix never hit the cache"
    assert off.prefix_hit_tokens == 0
    assert on.prefill_tokens_computed < off.prefill_tokens_computed
    assert on.cow_copies >= 1, "page-aligned duplicate must take COW path"


def test_bitwise_parity_chunked_vs_monolithic(toy_model):
    """Chunked prefill (cache off) == the PR 1 monolithic prefill, bitwise
    on the jnp fallback, across chunk sizes and prompt lengths that
    straddle chunk/bucket boundaries."""
    cfg, params = toy_model
    prompts = [
        [2 + (j * 5) % 60 for j in range(n)] for n in (3, 16, 40, 64, 90)
    ]
    jobs = [(p, 12, dict(top_k=1, termination_id=10 ** 9)) for p in prompts]
    jobs.append((prompts[2], 12,
                 dict(temperature=0.7, top_p=0.8, seed=3,
                      termination_id=10 ** 9)))

    mono = _engine(cfg, params, prefill_chunk=0)
    res_mono = _run(mono, jobs)
    for chunk in (16, 32, 64):
        ch = _engine(cfg, params, prefix_cache=False, prefill_chunk=chunk)
        res_ch = _run(ch, jobs)
        for (t1, lp1, _), (t2, lp2, _) in zip(res_mono, res_ch):
            assert t1 == t2, f"tokens diverged at chunk={chunk}"
            assert lp1 == lp2, f"log-probs diverged at chunk={chunk}"


def test_log_prob_requests_skip_match_but_feed_cache(toy_model):
    """return_log_probs recomputes the whole prompt (chunked teacher-forced
    scores match the monolithic path exactly) and still caches its pages
    for later non-scoring requests."""
    cfg, params = toy_model
    prompt = SHARED[:40]

    mono = _engine(cfg, params, prefill_chunk=0)
    (_, _, plp_mono), = _run(
        mono, [(prompt, 6, dict(top_k=1, termination_id=10 ** 9,
                                return_log_probs=True))])
    eng = _engine(cfg, params, prefix_cache=True)
    (_, _, plp_ch), = _run(
        eng, [(prompt, 6, dict(top_k=1, termination_id=10 ** 9,
                               return_log_probs=True))])
    assert plp_ch == plp_mono  # chunk-accumulated == monolithic, exactly
    assert eng.prefix_hit_tokens == 0
    # the scoring request's pages are now reusable
    (_, _, _), = _run(eng, [(prompt, 6, dict(top_k=1,
                                             termination_id=10 ** 9))])
    assert eng.prefix_hit_tokens > 0


# ---------------------------------------------------------------------------
# COW and refcount invariants
# ---------------------------------------------------------------------------


def _assert_page_states(eng):
    """Every page is free XOR referenced XOR cached-idle; refcounts equal
    the number of block tables holding the page."""
    from collections import Counter

    pool = eng.pool
    holders = Counter(p for r in eng._slots if r is not None
                      for p in r._pages)
    free = set(pool._free)
    assert NULL_PAGE not in free and holders.get(NULL_PAGE, 0) == 0
    for p in range(1, pool.num_pages):
        assert pool.refcounts[p] == holders.get(p, 0), \
            f"page {p}: refcount {pool.refcounts[p]} != holders {holders.get(p, 0)}"
        if p in free:
            assert pool.refcounts[p] == 0 and p not in pool.cached, \
                f"page {p} both free and referenced/cached"
    cached_idle = sum(1 for p in pool.cached if pool.refcounts[p] == 0)
    assert len(holders) + pool.num_free + cached_idle == pool.num_pages - 1


def test_cow_never_mutates_shared_page(toy_model):
    """A page-aligned fully-cached prompt re-admission copies the last
    shared page before the refeed tick writes it: the cached page's bytes
    are unchanged afterwards, and the copy produced identical output."""
    cfg, params = toy_model
    eng = _engine(cfg, params, prefix_cache=True)
    # cache pages 0..2 (positions 0..47) from a 53-token prompt
    (_, _, _), = _run(eng, [(SHARED + [5, 6, 7, 8, 9], 6,
                             dict(top_k=1, termination_id=10 ** 9))])
    cached_pages = sorted(eng.pool.cached)
    assert len(cached_pages) == 3
    before = {p: np.asarray(eng.pool.k[:, p]).copy() for p in cached_pages}

    # a page-aligned PREFIX of the cached prompt is fully covered: its
    # refeed tick would write the last shared page -> COW
    prompt = list(SHARED[:48])
    baseline = _engine(cfg, params, prefix_cache=False)
    (t1, _, _), = _run(baseline, [(prompt, 6, dict(top_k=1,
                                                   termination_id=10 ** 9))])
    (t2, _, _), = _run(eng, [(prompt, 6, dict(top_k=1,
                                              termination_id=10 ** 9))])
    assert eng.cow_copies == 1
    assert eng.prefill_tokens_computed > 0  # only the first prompt's chunks
    assert t2 == t1  # identical greedy continuation off the copied page
    for p in cached_pages:
        np.testing.assert_array_equal(
            before[p], np.asarray(eng.pool.k[:, p]),
            err_msg=f"shared page {p} mutated")
    _assert_page_states(eng)


def test_refcount_invariants_under_shared_stress(toy_model):
    """Churn shared-prefix traffic through a tight pool: refcounts stay
    exact at every step, shared pages are held by several block tables at
    once, and the pool drains whole (free + cached-idle)."""
    cfg, params = toy_model
    eng = _engine(cfg, params, max_slots=3, page_size=16, num_pages=17,
                  prefix_cache=True)
    rng = np.random.default_rng(1)
    families = [SHARED[:32], [9 + (j * 3) % 50 for j in range(32)]]
    reqs = []
    for i in range(14):
        fam = families[int(rng.integers(0, 2))]
        tail = [2 + int(x) for x in rng.integers(0, 60,
                                                 int(rng.integers(0, 12)))]
        plen_extra = int(rng.integers(1, 20))
        reqs.append(eng.submit(list(fam) + tail, plen_extra, top_k=1,
                               termination_id=10 ** 9))
    steps = 0
    saw_sharing = False
    while True:
        n = eng.step()
        steps += 1
        _assert_page_states(eng)
        from collections import Counter

        holders = Counter(p for r in eng._slots if r is not None
                          for p in r._pages)
        if any(c > 1 for c in holders.values()):
            saw_sharing = True
        if n == 0 and not eng._queue:
            break
        assert steps < 5000
    assert saw_sharing, "stress never exercised page sharing"
    for r in reqs:
        toks, _ = r.result(timeout=5)
        assert 1 <= len(r.generated) <= r.max_new_tokens
    assert int(eng.pool.refcounts.sum()) == 0
    assert eng.pool.num_free + len(eng.pool.cached) == eng.pool.num_pages - 1


# ---------------------------------------------------------------------------
# Eviction and admission under pressure
# ---------------------------------------------------------------------------


def test_eviction_under_pressure_admits_instead_of_starving(toy_model):
    """With most pages parked in the cache, a request whose worst case
    exceeds the FREE list must still admit by evicting cached-idle pages —
    pool exhaustion no longer means waiting while reusable pages sit
    idle."""
    cfg, params = toy_model
    eng = _engine(cfg, params, max_slots=2, page_size=16, num_pages=10,
                  prefix_cache=True)
    # park 3 pages in the cache (prompt 64 -> (64-1)//16 = 3 cacheable)
    prompt64 = [2 + (j * 7) % 60 for j in range(64)]
    _run(eng, [(prompt64, 4, dict(top_k=1, termination_id=10 ** 9))])
    assert len(eng.pool.cached) == 3
    parked = set(eng.pool.cached)
    free_before = eng.pool.num_free
    # worst case 7 pages > free list, but free + evictable covers it
    need = eng._max_pages_for(
        type("R", (), {"prompt": [0] * 80, "max_new_tokens": 30})())
    assert need > free_before
    prompt = [11 + (j * 13) % 50 for j in range(80)]
    (toks, _, _), = _run(eng, [(prompt, 30, dict(top_k=1,
                                                 termination_id=10 ** 9))])
    assert len(toks) == 110
    assert len(eng.pool.cached & parked) < 3, "nothing was evicted"
    _assert_page_states(eng)


def test_lru_leaf_first_eviction_order(toy_model):
    """Direct pool+trie unit test: eviction takes refcount-0 LEAVES in LRU
    order and never touches referenced pages."""
    cfg, params = toy_model
    pool = PagedKVPool(cfg, num_pages=12, page_size=4)
    cache = PrefixCache(pool, page_size=4)
    a = pool.alloc(3)  # chain A: 3 pages
    b = pool.alloc(2)  # chain B: 2 pages
    cache.insert(list(range(100, 112)), a, 3)
    cache.insert(list(range(200, 208)), b, 2)
    pool.release(a)
    pool.release(b)
    assert pool.num_evictable == 5
    # touch chain A so B becomes LRU
    got = cache.match(list(range(100, 112)), 3)
    assert got == a
    pool.release(got)
    freed = cache.evict(2)
    assert freed == [b[1], b[0]], "leaf-first LRU should drain chain B"
    # a referenced leaf is untouchable
    got = cache.match(list(range(100, 112)), 3)
    freed = cache.evict(10)
    assert freed == [] and len(cache) == 3
    pool.release(got)
    # now the whole A chain unwinds leaf-first
    assert cache.evict(10) == [a[2], a[1], a[0]]
    # evicted pages belong to the caller (alloc feeds them to the free
    # list); the trie is empty and nothing is cached or referenced
    assert len(cache) == 0 and not pool.cached
    assert int(pool.refcounts.sum()) == 0


def test_queue_overflow_raises_engine_overloaded(toy_model):
    cfg, params = toy_model
    eng = _engine(cfg, params, max_queue=2)
    eng.submit([2, 3], 4, top_k=1)
    eng.submit([2, 4], 4, top_k=1)
    with pytest.raises(EngineOverloaded):
        eng.submit([2, 5], 4, top_k=1)
    eng.run_until_idle()


# ---------------------------------------------------------------------------
# Chunked prefill scheduling
# ---------------------------------------------------------------------------


def test_prefill_interleaves_with_decode(toy_model):
    """Active decode slots keep generating while a long prompt prefills one
    chunk per tick — the monolithic stall is gone."""
    cfg, params = toy_model
    eng = _engine(cfg, params, max_slots=2, max_seq=256,
                  prefill_chunk=16, prefix_cache=False)
    short = eng.submit([2, 3, 4], 200, top_k=1, termination_id=10 ** 9)
    # admit + activate the short request
    while not short.generated:
        eng.step()
    long_prompt = [2 + (j * 7) % 60 for j in range(160)]  # 10 chunks
    long_req = eng.submit(long_prompt, 4, top_k=1, termination_id=10 ** 9)
    gen_before = len(short.generated)
    while long_req._phase in ("queued", "prefill"):
        eng.step()
    grown = len(short.generated) - gen_before
    assert grown >= 8, (
        f"decode stalled during chunked prefill (only {grown} tokens while "
        f"10 chunks filled)")
    eng.run_until_idle()
    long_req.result(timeout=30)
    short.result(timeout=30)
