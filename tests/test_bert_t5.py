"""BERT and T5 model families: forward shapes, masking semantics, datasets,
and end-to-end pretraining (reference analogs: model/bert_model.py,
model/t5_model.py, data/bert_dataset.py, data/t5_dataset.py,
pretrain_bert.py, pretrain_t5.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.models import make_config
from megatron_llm_tpu.models.bert import (
    bert_forward,
    bert_loss_from_batch,
    init_bert_params,
)
from megatron_llm_tpu.models.t5 import (
    init_t5_params,
    t5_forward,
    t5_loss_from_batch,
)


def bert_cfg(**kw):
    defaults = dict(
        num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=4, vocab_size=256, seq_length=32,
        max_position_embeddings=64, params_dtype="float32",
        use_flash_attn=False,
    )
    defaults.update(kw)
    return make_config("bert", **defaults)


def t5_cfg(**kw):
    defaults = dict(
        num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=4, vocab_size=256, seq_length=32,
        max_position_embeddings=64, params_dtype="float32",
        use_flash_attn=False,
    )
    defaults.update(kw)
    return make_config("t5", **defaults)


def test_bert_forward_shapes():
    cfg = bert_cfg()
    params = init_bert_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 250)
    pad = jnp.ones((2, 32))
    types = jnp.zeros((2, 32), jnp.int32)
    lm_logits, binary_logits = bert_forward(cfg, params, tokens, pad, types)
    v = params["embedding"]["word_embeddings"].shape[0]
    assert lm_logits.shape == (2, 32, v)
    assert binary_logits.shape == (2, 2)


def test_bert_attention_is_bidirectional_and_pad_masked():
    """Changing a LATER non-pad token changes an earlier position's logits
    (bidirectional); changing a PAD token changes nothing."""
    cfg = bert_cfg()
    params = init_bert_params(cfg, jax.random.PRNGKey(0))
    tokens = np.asarray(jax.random.randint(jax.random.PRNGKey(1), (1, 32), 0, 250))
    pad = np.ones((1, 32), np.float32)
    pad[0, 28:] = 0.0  # last 4 are padding

    base, _ = bert_forward(cfg, params, jnp.asarray(tokens), jnp.asarray(pad))
    t2 = tokens.copy()
    t2[0, 20] = (t2[0, 20] + 1) % 250  # later real token
    later, _ = bert_forward(cfg, params, jnp.asarray(t2), jnp.asarray(pad))
    assert not np.allclose(np.asarray(base[0, 5]), np.asarray(later[0, 5]))

    t3 = tokens.copy()
    t3[0, 30] = (t3[0, 30] + 7) % 250  # pad position
    padded, _ = bert_forward(cfg, params, jnp.asarray(t3), jnp.asarray(pad))
    np.testing.assert_allclose(
        np.asarray(base[0, :28]), np.asarray(padded[0, :28]), atol=1e-6
    )


def test_bert_loss_trains():
    from megatron_llm_tpu.data.bert_dataset import BertDataset

    class Docs:
        def __len__(self):
            return 8

        def __getitem__(self, i):
            rng = np.random.RandomState(i)
            return rng.randint(1, 250, size=40)

    ds = BertDataset(Docs(), 4, 32, 256, cls_id=252, sep_id=253,
                     mask_id=254, pad_id=0)
    batch = {k: jnp.asarray(np.stack([ds[i][k] for i in range(4)]))
             for k in ds[0]}
    cfg = bert_cfg()
    params = init_bert_params(cfg, jax.random.PRNGKey(0))

    loss_fn = jax.jit(lambda p: bert_loss_from_batch(cfg, p, batch)[0])
    grad_fn = jax.jit(jax.grad(lambda p: bert_loss_from_batch(cfg, p, batch)[0]))
    l0 = float(loss_fn(params))
    for _ in range(60):
        g = grad_fn(params)
        params = jax.tree.map(lambda w, gg: w - 0.1 * gg, params, g)
    l1 = float(loss_fn(params))
    assert np.isfinite(l0) and l1 < l0, (l0, l1)


def test_bert_dataset_masking_stats():
    from megatron_llm_tpu.data.bert_dataset import BertDataset

    class Docs:
        def __len__(self):
            return 20

        def __getitem__(self, i):
            rng = np.random.RandomState(100 + i)
            return rng.randint(1, 250, size=60)

    ds = BertDataset(Docs(), 200, 64, 256, cls_id=252, sep_id=253,
                     mask_id=254, pad_id=0)
    n_masked, n_tokens, n_random = 0, 0, 0
    for i in range(200):
        s = ds[i]
        real = int(s["padding_mask"].sum())
        masked = int(s["loss_mask"].sum())
        n_tokens += real
        n_masked += masked
        n_random += int(s["is_random"])
        # masked positions carry the ORIGINAL token as label
        pos = np.nonzero(s["loss_mask"])[0]
        assert np.all(s["labels"][pos] >= 0)
        # [CLS] (position 0) is never selected for masking, and no masked
        # label is a special token (the 10% random replacement MAY write a
        # special id into text, matching the reference's full-vocab sampling)
        assert 0 not in pos
        assert not set(s["labels"][pos].tolist()) & {252, 253}
    frac = n_masked / n_tokens
    assert 0.10 < frac < 0.20, frac           # ~15% masking
    assert 0.3 < n_random / 200 < 0.7          # ~50% random-next pairs


def test_t5_forward_shapes_and_cross_attention():
    cfg = t5_cfg()
    params = init_t5_params(cfg, jax.random.PRNGKey(0))
    enc = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 250)
    dec = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, 250)
    em = jnp.ones((2, 32))
    dm = jnp.ones((2, 16))
    logits = t5_forward(cfg, params, enc, dec, em, dm)
    v = params["embedding"]["word_embeddings"].shape[0]
    assert logits.shape == (2, 16, v)

    # changing the encoder input changes decoder logits (cross attention live)
    enc2 = enc.at[0, 5].set((enc[0, 5] + 1) % 250)
    logits2 = t5_forward(cfg, params, enc2, dec, em, dm)
    assert not np.allclose(np.asarray(logits[0]), np.asarray(logits2[0]))

    # decoder self-attention is causal: changing a later decoder token leaves
    # earlier positions unchanged
    dec2 = dec.at[0, 10].set((dec[0, 10] + 1) % 250)
    logits3 = t5_forward(cfg, params, enc, dec2, em, dm)
    np.testing.assert_allclose(
        np.asarray(logits[0, :10]), np.asarray(logits3[0, :10]), atol=1e-6
    )


def test_t5_span_corruption_roundtrip():
    from megatron_llm_tpu.data.t5_dataset import corrupt_spans

    rng = np.random.RandomState(0)
    tokens = np.arange(1, 101)
    sentinels = [250, 251, 252, 253, 254, 255]
    enc, target = corrupt_spans(tokens, sentinels, rng)
    # every corrupted token appears exactly once in enc or target
    enc_real = [t for t in enc if t not in sentinels]
    tgt_real = [t for t in target if t not in sentinels]
    assert sorted(enc_real + tgt_real) == tokens.tolist()
    # ~15% of tokens are in the target spans
    assert 0.05 <= len(tgt_real) / len(tokens) <= 0.30
    # sentinels pair up: each sentinel in enc appears in target
    enc_sent = [t for t in enc if t in sentinels]
    tgt_sent = [t for t in target if t in sentinels]
    assert enc_sent == tgt_sent


def test_t5_loss_trains():
    from megatron_llm_tpu.data.t5_dataset import T5Dataset

    class Docs:
        def __len__(self):
            return 8

        def __getitem__(self, i):
            rng = np.random.RandomState(i)
            return rng.randint(1, 240, size=50)

    ds = T5Dataset(Docs(), 4, 32, 16, sentinel_ids=[250, 251, 252, 253],
                   bos_id=248, eos_id=249, pad_id=0)
    batch = {k: jnp.asarray(np.stack([ds[i][k] for i in range(4)]))
             for k in ds[0]}
    cfg = t5_cfg()
    params = init_t5_params(cfg, jax.random.PRNGKey(0))
    loss_fn = jax.jit(lambda p: t5_loss_from_batch(cfg, p, batch)[0])
    grad_fn = jax.jit(jax.grad(lambda p: t5_loss_from_batch(cfg, p, batch)[0]))
    l0 = float(loss_fn(params))
    for _ in range(60):
        g = grad_fn(params)
        params = jax.tree.map(lambda w, gg: w - 0.1 * gg, params, g)
    l1 = float(loss_fn(params))
    assert np.isfinite(l0) and l1 < l0, (l0, l1)


def test_bert_tp_sharding_matches_single(eight_devices):
    """BERT logits under tp=4 == single device (param sharding rules cover
    the new mlm_head/pooler/binary_head/tokentype params)."""
    from megatron_llm_tpu.core.parallel_state import build_mesh, global_mesh
    from megatron_llm_tpu.parallel.tp import param_shardings

    cfg = bert_cfg()
    params = init_bert_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 250)
    pad = jnp.ones((2, 32))
    ref, ref_bin = bert_forward(cfg, params, tokens, pad)

    cfgN = bert_cfg(tensor_model_parallel_size=4)
    mesh = build_mesh(tensor_model_parallel_size=4, devices=eight_devices[:4])
    with global_mesh(mesh):
        sharded = jax.device_put(params, param_shardings(mesh, params))
        got, got_bin = jax.jit(
            lambda p, t: bert_forward(cfgN, p, t, pad)
        )(sharded, tokens)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(ref_bin), np.asarray(got_bin),
                               atol=2e-4, rtol=2e-4)


def test_pretrain_bert_cli_end_to_end(tmp_path):
    """pretrain_bert entry path: corpus -> provider -> pretrain loop."""
    from megatron_llm_tpu.config import Config, apply_architecture
    from megatron_llm_tpu.data.indexed_dataset import make_builder
    from megatron_llm_tpu.models.bert import bert_loss_from_batch, init_bert_params
    from megatron_llm_tpu.training import pretrain
    from pretrain_bert import bert_data_provider

    prefix = str(tmp_path / "corpus_text_document")
    rng = np.random.RandomState(0)
    b = make_builder(prefix + ".bin", vocab_size=250)
    for _ in range(40):
        b.add_doc(rng.randint(1, 250, size=rng.randint(30, 80)))
    b.finalize(prefix + ".idx")

    cfg = Config()
    apply_architecture(cfg, "bert")
    cfg.model.num_layers = 2
    cfg.model.hidden_size = 64
    cfg.model.num_attention_heads = 4
    cfg.model.vocab_size = 256
    cfg.model.max_position_embeddings = 64
    cfg.data.seq_length = 32
    cfg.data.data_path = [prefix]
    cfg.data.tokenizer_type = "NullTokenizer"
    cfg.training.params_dtype = "float32"
    cfg.training.use_flash_attn = False
    cfg.training.micro_batch_size = 4
    cfg.training.global_batch_size = 4
    cfg.training.train_iters = 4
    cfg.training.eval_iters = 1
    cfg.training.eval_interval = 2
    cfg.logging.log_interval = 2
    cfg.finalize(n_devices=1)

    result = pretrain(
        cfg,
        data_iterators_provider=bert_data_provider,
        params_provider=lambda key: init_bert_params(cfg, key),
        loss_fn=bert_loss_from_batch,
    )
    assert result["iteration"] == 4
    assert np.isfinite(float(result["last_metrics"]["lm loss"]))
