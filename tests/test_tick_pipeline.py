"""Pipelined multi-tick dispatch tests (ISSUE 17).

Gates:

1. **Parity matrix** — ``--tick_pipeline_depth`` ∈ {1, 2, 3} emits
   tokens AND log-probs bitwise-identical to depth 0 (today's
   one-tick-per-launch driver) across: greedy and sampled rows, prefix
   cache on/off, every stop mode (termination id, EOL, double-EOL)
   actually FIRING on device, mixed admission/prefill boundaries,
   preemption/resume mid-pipeline, and contention under the priority
   and slo scheduling policies.
2. **Lag-boundary correctness** — a preemption landing while a chain is
   in flight discards the overrun ticks and the victim's resume replays
   them bitwise (the ``fold_in(key, step)`` stream); stop tokens and
   token budgets detected in-program freeze the row exactly where the
   host's apply rules would.
3. **Ledger safety** — pre-granted page budgets (``_pregrant_locked``)
   never fail an in-flight alloc on a tight pool, and every page comes
   back after drain (no leaks vs the depth-0 run).
4. **Degradation** — speculative engines ignore the flag (depth-0 per
   tick acceptance) and depth 0 itself never touches pipeline state.
5. **Telemetry** — ``engine-chained-tick`` spans carry chain/host-gap
   attrs, the in-flight gauge returns to 0 at drain, and chains
   measurably reduce host dispatch count.
6. **graftcheck** — the chained builder's traced bodies are in the
   sync-in-jit analyzed set (builder-factory convention), and a
   builder factory hiding a host sync is flagged.
"""

import os
import sys

import numpy as np
import pytest

import jax

from megatron_llm_tpu.generation import ContinuousBatchingEngine, DraftModel

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# big enough that the GPT-2 EOL (198) / double-EOL (628) ids are real
# outputs — the device-side stop modes must actually fire, not idle
VOCAB = 700


@pytest.fixture(scope="module")
def models():
    from megatron_llm_tpu.models import init_model_params, make_config

    def mk(layers, hidden, heads, nkv, ffn):
        return make_config(
            "llama2", num_layers=layers, hidden_size=hidden,
            num_attention_heads=heads, num_attention_heads_kv=nkv,
            ffn_hidden_size=ffn, seq_length=256,
            max_position_embeddings=256, vocab_size=VOCAB,
            hidden_dropout=0.0, attention_dropout=0.0,
            params_dtype="float32", use_flash_attn=False,
        )

    cfg = mk(2, 64, 4, 2, 128)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    dcfg = mk(1, 32, 2, 2, 64)
    dparams = init_model_params(dcfg, jax.random.PRNGKey(1))
    return {"cfg": cfg, "params": params,
            "draft": DraftModel(dcfg, dparams)}


def _engine(models, depth, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_seq", 128)
    kw.setdefault("ragged", True)
    return ContinuousBatchingEngine(models["cfg"], models["params"], None,
                                    tick_pipeline_depth=depth, **kw)


def _run(eng, jobs):
    reqs = [eng.submit(p, n, **kw) for p, n, kw in jobs]
    eng.run_until_idle()
    return [r.result(timeout=120) for r in reqs]


def _assert_bitwise(a, b, what="pipelined"):
    assert len(a) == len(b)
    for k, ((t0, l0), (t1, l1)) in enumerate(zip(a, b)):
        assert t0 == t1, f"row {k}: {what} tokens diverged from depth 0"
        assert l0 == l1, f"row {k}: {what} log-prob bits diverged"


def _steady_jobs(n_new=14):
    """Greedy + sampled rows, every stop mode armed, budgets that expire
    mid-chain (not multiples of any depth), a shared prefix (cache/COW
    traffic) and a long prompt (admission/prefill boundary mid-run)."""
    shared = [2 + (i * 7) % 60 for i in range(48)]  # 3 full pages @ 16
    return [
        ([5, 9, 2], n_new, dict(top_k=1, termination_id=10 ** 9)),
        ([7, 3], 11, dict(temperature=0.9, top_k=7, seed=42,
                          termination_id=10 ** 9)),
        ([11, 4, 6], n_new + 3, dict(top_k=1, stop_on_eol=True)),
        ([9, 9, 1], n_new + 3, dict(top_k=1, stop_on_double_eol=True)),
        (list(shared), 9, dict(top_k=1, termination_id=10 ** 9)),
        (shared + [3, 4, 5], 9, dict(top_k=1, termination_id=10 ** 9)),
        ([6, 1], 7, dict(temperature=1.1, top_k=0, top_p=0.9, seed=7,
                         termination_id=10 ** 9)),
    ]


# ---------------------------------------------------------------------------
# 1. parity matrix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cache", [True, False])
@pytest.mark.parametrize("depth", [1, 2, 3])
def test_parity_matrix(models, cache, depth):
    base = _run(_engine(models, 0, prefix_cache=cache), _steady_jobs())
    got = _run(_engine(models, depth, prefix_cache=cache), _steady_jobs())
    _assert_bitwise(base, got, f"depth {depth}")


def test_parity_termination_fires_mid_chain(models):
    """The device-side termination-id detector stops a row exactly where
    the host would: pick the id off the depth-0 greedy stream so the
    stop genuinely fires inside a chain, with a second row decoding
    past it (freeze must not perturb the survivor)."""
    probe = _run(_engine(models, 0),
                 [([5, 9, 2], 30, dict(top_k=1, termination_id=10 ** 9))])
    term = probe[0][0][3:][7]
    jobs = [([5, 9, 2], 30, dict(top_k=1, termination_id=term)),
            ([7, 3], 30, dict(top_k=1, termination_id=10 ** 9))]
    base = _run(_engine(models, 0), jobs)
    assert base[0][0][-1] == term, "probe id never fired — dead test"
    for depth in (1, 2, 3):
        _assert_bitwise(base, _run(_engine(models, depth), jobs),
                        f"depth {depth} termination")


def test_parity_eol_stop_modes_fire(models):
    """EOL / double-EOL stop modes run in-program: find a sampled stream
    that really emits EOL (198), then check stop_on_eol halts on it and
    stop_on_double_eol correctly does NOT halt on a single EOL —
    bitwise against depth 0 either way."""
    hit = None
    for seed in range(30):
        eng = _engine(models, 0)
        r = eng.submit([5, 9, 2], 40, temperature=1.3, top_k=0,
                       seed=seed, termination_id=10 ** 9)
        eng.run_until_idle()
        if 198 in r.result(timeout=60)[0][3:]:
            hit = seed
            break
    assert hit is not None, "no sampled stream emitted EOL — dead test"
    jobs = [([5, 9, 2], 40, dict(temperature=1.3, top_k=0, seed=hit,
                                 stop_on_eol=True)),
            ([5, 9, 2], 40, dict(temperature=1.3, top_k=0, seed=hit,
                                 stop_on_double_eol=True))]
    base = _run(_engine(models, 0), jobs)
    assert base[0][0][-1] in (198, 628), "EOL mode never stopped"
    assert len(base[1][0]) >= len(base[0][0]), (
        "double-EOL mode stopped no later than single-EOL — suspicious")
    for depth in (1, 2):
        _assert_bitwise(base, _run(_engine(models, depth), jobs),
                        f"depth {depth} eol")


def test_parity_preempt_mid_pipeline(models):
    """Force-preempt a decoding request between pipelined steps — with a
    chain in flight, the overrun ticks are discarded and the resume
    replays them bitwise (fold_in(key, step) stream)."""
    def run(depth, preempt_at):
        eng = _engine(models, depth, sched_policy="fcfs")
        long = [2 + (j * 7) % 60 for j in range(48)]
        req = eng.submit(long, 14, top_k=1, termination_id=10 ** 9)
        other = eng.submit([5, 9, 2], 6, top_k=1, termination_id=10 ** 9)
        steps = preempted_in_flight = 0
        while not req.finished:
            eng.step()
            steps += 1
            if steps == preempt_at and req._phase == "decode":
                if depth and eng._inflight:
                    preempted_in_flight = 1
                assert eng.preempt(req)
        eng.run_until_idle()
        return ([req.result(timeout=120), other.result(timeout=120)],
                preempted_in_flight)

    base, _ = run(0, 10 ** 9)  # never preempted
    in_flight_seen = 0
    for depth in (0, 1, 2):
        for cut in (3, 5):
            got, inflight = run(depth, cut)
            _assert_bitwise(base, got, f"depth {depth} preempt@{cut}")
            in_flight_seen += inflight
    assert in_flight_seen, (
        "no preemption ever landed with a chain in flight — the lag "
        "boundary was never exercised")


@pytest.mark.parametrize("policy", ["priority", "slo"])
def test_parity_under_contention_policies(models, policy):
    """Admission-time scheduler decisions (priority order, EDF) are
    boundary work — depth 2 under slot contention stays bitwise."""
    def jobs():
        out = []
        for i in range(5):
            kw = dict(top_k=1, termination_id=10 ** 9)
            if policy == "priority":
                kw["priority"] = i % 3
            else:
                kw["ttft_deadline_ms"] = 60_000 + 10_000 * i
            out.append(([5 + i, 9, 2 + i], 10 + i, kw))
        return out

    base = _run(_engine(models, 0, max_slots=2, sched_policy=policy),
                jobs())
    got = _run(_engine(models, 2, max_slots=2, sched_policy=policy),
               jobs())
    _assert_bitwise(base, got, f"{policy} depth 2")


# ---------------------------------------------------------------------------
# 3 + 4. ledger safety on a tight pool; degradation rules
# ---------------------------------------------------------------------------


def test_ledger_safety_tight_pool(models):
    """Pre-granted budgets draw pages EARLY (up to 2·depth positions
    ahead) but never more than admission committed: on a pool sized to
    the bone, no in-flight alloc fails, results stay bitwise, and every
    page returns to the free list at drain."""
    kw = dict(max_slots=4, page_size=16, num_pages=40, prefix_cache=False)
    jobs = [([5 + i, 9, 2 + i], 40, dict(top_k=1, termination_id=10 ** 9))
            for i in range(4)]
    eng0 = _engine(models, 0, **kw)
    base = _run(eng0, jobs)
    eng2 = _engine(models, 2, **kw)
    got = _run(eng2, jobs)
    _assert_bitwise(base, got, "tight-pool depth 2")
    assert eng2.pool.num_free == eng0.pool.num_free, "pages leaked"
    assert not eng2._inflight and eng2._pipe_state is None


def test_spec_engines_degrade_to_depth0(models):
    """Speculative decoding needs per-tick acceptance on the host — the
    flag is ignored (never chains) and results are bitwise the spec
    depth-0 run."""
    kw = dict(spec_k=3, spec_draft=models["draft"], spec_adaptive=False)
    jobs = [j for j in _steady_jobs() if "temperature" not in j[2]]
    base = _run(_engine(models, 0, **kw), jobs)
    eng = _engine(models, 2, **kw)
    got = _run(eng, jobs)
    _assert_bitwise(base, got, "spec depth 2")
    assert eng._chained_fn is None, "spec engine built the chained tick"
    assert not eng._inflight and eng._pipe_state is None


def test_depth0_never_touches_pipeline_state(models):
    """Depth 0 is the seed driver byte for byte: no chain program, no
    in-flight state — only the (new, always-on) host-gap bookkeeping."""
    eng = _engine(models, 0)
    _run(eng, _steady_jobs()[:3])
    assert eng.pipeline_depth == 0
    assert eng._chained_fn is None
    assert not eng._inflight and eng._pipe_state is None
    stats = eng.host_gap_stats()
    assert stats["count"] > 0 and stats["p50_ms"] <= stats["p99_ms"]


# ---------------------------------------------------------------------------
# 5. telemetry: spans, gauges, measurably fewer host dispatches
# ---------------------------------------------------------------------------


def test_chained_span_and_inflight_gauge(models):
    from megatron_llm_tpu.observability import registry as obs_registry
    from megatron_llm_tpu.observability import trace as obs_trace

    old = obs_trace.get_tracer()
    tracer = obs_trace.configure(capacity=4096)
    try:
        eng = _engine(models, 2)
        _run(eng, _steady_jobs()[:4])
    finally:
        obs_trace._TRACER = old
    # events are (ph, name, ts, dur, ident, args) tuples
    spans = [e for e in tracer.snapshot()
             if e[1] == "engine-chained-tick"]
    assert spans, "no chained-tick spans recorded"
    assert all((e[5] or {}).get("chain") == 2 for e in spans)
    gaps = [(e[5] or {}).get("host_gap_ms") for e in spans]
    assert any(g is not None and g >= 0 for g in gaps), (
        "no chained span carried a host-gap attr")
    reg = obs_registry.get_registry()
    text = reg.render()
    assert "mlt_engine_host_gap_seconds" in text
    assert "mlt_engine_inflight_ticks" in text
    assert "mlt_engine_tick_pipeline_depth" in text
    assert reg.gauge("mlt_engine_inflight_ticks").value == 0, (
        "in-flight gauge did not return to 0 at drain")


def test_chaining_reduces_host_dispatches(models):
    """The point of the PR: N-tick chains mean ~N× fewer host dispatch
    boundaries for the same token stream."""
    jobs = [([5 + i, 9, 2], 24, dict(top_k=1, termination_id=10 ** 9))
            for i in range(4)]
    count0 = _engineed_dispatches(models, 0, jobs)
    count2 = _engineed_dispatches(models, 2, jobs)
    assert count2 < count0 * 0.7, (count0, count2)


def _engineed_dispatches(models, depth, jobs):
    eng = _engine(models, depth)
    _run(eng, jobs)
    return eng.host_gap_stats()["count"]


# ---------------------------------------------------------------------------
# 6. graftcheck: the chained builder is analyzed; bad builders flag
# ---------------------------------------------------------------------------


def test_chained_builder_in_traced_set():
    """The builder-factory convention (module-level ``make_*_fn``)
    reaches the ragged/chained tick bodies the per-file resolver cannot
    — the compiled chain really is sync-analyzed."""
    from tools.graftcheck import core
    from tools.graftcheck.rules.sync import SyncInJitRule

    path = os.path.join(REPO, "megatron_llm_tpu", "generation",
                        "ragged.py")
    ctx = core.FileContext(path)
    names = {getattr(n, "name", "<lambda>")
             for n in SyncInJitRule()._traced_nodes(ctx)}
    assert {"chained", "body", "target_forward"} <= names, names
    # the factory body itself runs at build time (host side) — exempt
    assert "make_chained_tick_fn" not in names


def test_builder_factory_sync_flagged():
    """A chained builder hiding a host sync inside the compiled body is
    a finding; a jax-free host-side factory (REST client shape) is not
    traced at all."""
    from tools.graftcheck import core
    from tools.graftcheck.rules import ALL_RULES as _RULES

    bad = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def make_bad_tick_fn(cfg):\n"
        "    def tick(x):\n"
        "        return np.asarray(x) + jnp.ones(())\n"
        "    return tick\n"
    )
    hits = [f for f in core.check_file("fixture.py", _RULES, source=bad)
            if f.rule == "sync-in-jit"]
    assert len(hits) == 1 and hits[0].line == 6, hits
    host = (
        "import requests\n"
        "def make_api_generate_fn(url):\n"
        "    def fn(text):\n"
        "        return float(requests.get(url).elapsed.total_seconds())\n"
        "    return fn\n"
    )
    assert not [f for f in core.check_file("fixture.py", _RULES, source=host)
                if f.rule == "sync-in-jit"]
