"""Weight-only int8 inference (ops/quant.py — beyond-reference; the
reference decode reads fp16 weights, text_generation/generation.py:89)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.models import (
    init_model_params,
    make_config,
    model_forward,
)
from megatron_llm_tpu.ops.quant import (
    _quantize_kernel,
    int8_quant_error_bound,
    quantize_layer_weights_int8,
)


def _logits(res):
    """model_forward returns (logits, aux...) tuples on some paths."""
    x = res[0] if isinstance(res, tuple) else res
    return np.asarray(x, np.float32)


def _cfg(**kw):
    name = kw.pop("model_name", "llama2")
    d = dict(num_layers=2, hidden_size=64, num_attention_heads=4,
             num_attention_heads_kv=2, vocab_size=256, params_dtype="float32",
             max_position_embeddings=128, use_flash_attn=False)
    d.update(kw)
    return make_config(name, **d)


def test_dequant_error_bound():
    k = jax.random.normal(jax.random.PRNGKey(0), (32, 48)) * 0.3
    q = _quantize_kernel(k)
    assert q["kernel_q"].dtype == jnp.int8
    deq = q["kernel_q"].astype(jnp.float32) * q["kernel_scale"][None, :]
    err = float(jnp.max(jnp.abs(deq - k)))
    assert err <= int8_quant_error_bound(k) + 1e-7


def test_dequant_glu_and_stacked_axes():
    # GLU fc1 [in, 2, ffn]: contraction axis -3 (keyed on the param path,
    # not shape alone — ADVICE r4 #1); stacked [L, in, out]: -2
    k_glu = jax.random.normal(jax.random.PRNGKey(1), (16, 2, 24))
    q = _quantize_kernel(k_glu, "fc1")
    assert q["kernel_scale"].shape == (2, 24)
    deq = q["kernel_q"].astype(jnp.float32) * q["kernel_scale"][None]
    assert float(jnp.max(jnp.abs(deq - k_glu))) <= (
        int8_quant_error_bound(k_glu, "fc1") + 1e-7)

    k_st = jax.random.normal(jax.random.PRNGKey(2), (3, 16, 24))
    qs = _quantize_kernel(k_st)
    assert qs["kernel_scale"].shape == (3, 24)

    # a NON-fc1 stacked kernel whose penultimate dim happens to be 2 must
    # quantize along -2 like any plain kernel (the old shape sniff would
    # silently pick -3)
    k_trap = jax.random.normal(jax.random.PRNGKey(3), (3, 16, 2))
    qt = _quantize_kernel(k_trap, "out_proj")
    assert qt["kernel_scale"].shape == (3, 2)


def test_logits_close_and_structure():
    cfg = _cfg()
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    qparams = quantize_layer_weights_int8(params)
    # untouched outside the layer stack
    assert "kernel" in qparams["lm_head"]
    assert qparams["embedding"] is params["embedding"]
    # quantized inside
    qkv = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(lambda x: x.dtype == jnp.int8,
                               qparams["layers"]))
    assert any(qkv)

    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 256)
    ref = _logits(model_forward(cfg, params, tok))
    out = _logits(model_forward(cfg, qparams, tok))
    # W8A16 on a random-init tiny model: logits track closely and the
    # argmax rarely moves
    assert np.max(np.abs(ref - out)) < 0.25 * (np.max(np.abs(ref)) + 1.0)
    agree = (ref.argmax(-1) == out.argmax(-1)).mean()
    assert agree > 0.9, f"top-1 agreement {agree}"


def test_moe_experts_quantized_router_kept():
    cfg = make_config("mixtral", num_layers=2, hidden_size=64,
                      num_attention_heads=4, num_attention_heads_kv=2,
                      vocab_size=256, params_dtype="float32",
                      max_position_embeddings=128, num_experts=4,
                      moe_router_topk=2, use_flash_attn=False)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    qparams = quantize_layer_weights_int8(params)
    moe = qparams["layers"]["moe"]
    # router stays fp32 (routing is precision-sensitive, [h,E] negligible)
    assert "kernel" in moe["router"]
    # expert stacks ARE quantized, with per-expert channel scales
    assert moe["experts"]["fc1"]["kernel_q"].dtype == jnp.int8
    assert moe["experts"]["fc2"]["kernel_q"].dtype == jnp.int8
    assert "kernel_q" in qparams["layers"]["attention"]["qkv"]
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 256)
    ref = _logits(model_forward(cfg, params, tok))
    out = _logits(model_forward(cfg, qparams, tok))
    assert np.isfinite(out).all()
    assert np.max(np.abs(ref - out)) < 1.0


def test_generation_with_int8_engine():
    """The full decode path (KV cache, while_loop) with int8 weights via
    the cfg.inference.int8_weights switch on InferenceEngine."""
    from megatron_llm_tpu.generation import InferenceEngine

    class _Tok:
        vocab_size = 256
        eod = 0

        def tokenize(self, s):
            return [min(ord(c), 255) for c in s]

        def detokenize(self, ids):
            return "".join(chr(max(1, i)) for i in ids)

    cfg = _cfg()
    cfg.inference.int8_weights = True
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    eng = InferenceEngine(cfg, params, _Tok())
    assert "kernel_q" in eng.params["layers"]["attention"]["qkv"]
    out = eng.generate(["ab"], tokens_to_generate=4)
    text = out[0] if isinstance(out, (list, tuple)) else out
    assert text is not None


def test_int8_plus_fp8_rejected():
    from megatron_llm_tpu.generation import InferenceEngine

    cfg = _cfg()
    cfg.model.fp8 = "e4m3"
    cfg.inference.int8_weights = True
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="mutually exclusive"):
        InferenceEngine(cfg, params, None)


def test_quantized_tree_sharding_specs():
    """Multi-chip TP with int8 weights: kernel_q takes the kernel's spec
    (same shape/axes) and kernel_scale the bias-shaped rule — specs must
    never exceed leaf rank (parallel/tp.py rule extension)."""
    import jax.tree_util as tu

    from megatron_llm_tpu.parallel.tp import param_partition_specs

    moe_cfg = make_config("mixtral", num_layers=2, hidden_size=64,
                          num_attention_heads=4, num_attention_heads_kv=2,
                          vocab_size=256, params_dtype="float32",
                          max_position_embeddings=128, num_experts=4,
                          moe_router_topk=2, use_flash_attn=False)
    for cfg in (_cfg(), moe_cfg):
        params = init_model_params(cfg, jax.random.PRNGKey(0))
        q = quantize_layer_weights_int8(params)
        specs = param_partition_specs(q)
        for (path, leaf), spec in zip(tu.tree_flatten_with_path(q)[0],
                                      tu.tree_leaves(specs)):
            assert len(tuple(spec)) <= leaf.ndim, (path, spec, leaf.shape)
    qkv = specs["layers"]["attention"]["qkv"]
    # column-parallel: fused head dim sharded for the int8 kernel too
    assert tuple(qkv["kernel_q"])[-1] == "tp"
    assert tuple(qkv["kernel_scale"])[-1] == "tp"
    # expert stacks (leading layer-stack axis, then E): ep on the expert
    # axis for both quantized leaves
    fc1 = specs["layers"]["moe"]["experts"]["fc1"]
    assert tuple(fc1["kernel_q"])[1] == "ep", tuple(fc1["kernel_q"])
    assert tuple(fc1["kernel_scale"])[1] == "ep", tuple(fc1["kernel_scale"])
