"""Chunked-vocab cross entropy (ops/cross_entropy.py:
chunked_softmax_cross_entropy_from_hidden) — the head-fused CE that never
materializes full logits. Gate: exact match (values AND grads) with the
unchunked path; the reference analog is the vocab-parallel CE's
three-quantity bookkeeping (cross_entropy.py:21-60), cut sequentially."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.models import init_model_params, make_config
from megatron_llm_tpu.models.language_model import loss_from_batch
from megatron_llm_tpu.ops.cross_entropy import (
    chunked_softmax_cross_entropy_from_hidden,
    softmax_cross_entropy,
)


@pytest.mark.parametrize("num_chunks,bias", [(4, False), (8, True), (1, False)])
def test_chunked_matches_exact(num_chunks, bias):
    h, v = 32, 64
    key = jax.random.PRNGKey(0)
    hidden = jax.random.normal(key, (2, 16, h))
    w = jax.random.normal(jax.random.fold_in(key, 1), (h, v))
    b = jax.random.normal(jax.random.fold_in(key, 2), (v,)) if bias else None
    labels = jax.random.randint(jax.random.fold_in(key, 3), (2, 16), 0, v)

    def exact(hd, wk):
        logits = hd @ wk
        if b is not None:
            logits = logits + b
        return softmax_cross_entropy(logits, labels).sum()

    def chunked(hd, wk):
        return chunked_softmax_cross_entropy_from_hidden(
            hd, wk, labels, num_chunks, head_bias=b
        ).sum()

    (l1, g1) = jax.value_and_grad(exact, (0, 1))(hidden, w)
    (l2, g2) = jax.value_and_grad(chunked, (0, 1))(hidden, w)
    np.testing.assert_allclose(float(l2), float(l1), rtol=1e-6)
    for a, bb in zip(g1, g2):
        # fp32 accumulation-order noise between the chunked and monolithic
        # logsumexp formulations
        np.testing.assert_allclose(np.asarray(bb), np.asarray(a),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("tied", [False, True])
def test_model_loss_chunked_matches_unchunked(tied):
    cfg = make_config(
        "llama2" if not tied else "gpt", num_layers=2, hidden_size=64,
        num_attention_heads=4, vocab_size=256, seq_length=32,
        max_position_embeddings=64, params_dtype="float32",
        use_flash_attn=False,
    )
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0, 256)
    batch = {"tokens": tok[:, :-1], "labels": tok[:, 1:],
             "loss_mask": jnp.ones((2, 32), jnp.float32)}

    ref_loss, ref_grads = jax.jit(jax.value_and_grad(
        lambda p: loss_from_batch(cfg, p, batch)[0]))(params)
    cfg.model.ce_vocab_chunks = 4
    loss, grads = jax.jit(jax.value_and_grad(
        lambda p: loss_from_batch(cfg, p, batch)[0]))(params)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(ref_grads)[0],
        jax.tree_util.tree_flatten_with_path(grads)[0],
    ):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-5, atol=1e-6,
                                   err_msg=f"grad mismatch at {pa}")


def test_chunked_ce_tp_parity():
    """Under a tp=2 mesh the chunked scan must reproduce the unsharded loss
    (GSPMD reshapes the tp-sharded vocab axis across chunks)."""
    from megatron_llm_tpu.core.parallel_state import build_mesh, global_mesh
    from megatron_llm_tpu.parallel.tp import batch_shardings, param_shardings

    common = dict(num_layers=2, hidden_size=64, num_attention_heads=4,
                  vocab_size=256, seq_length=32, max_position_embeddings=64,
                  params_dtype="float32", use_flash_attn=False,
                  ce_vocab_chunks=4)
    cfg = make_config("llama2", **common)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0, 256)
    batch = {"tokens": tok[:, :-1], "labels": tok[:, 1:],
             "loss_mask": jnp.ones((2, 32), jnp.float32)}

    def run(mesh, cfg):
        with global_mesh(mesh):
            p = jax.device_put(params, param_shardings(mesh, params))
            b = jax.device_put(batch, batch_shardings(cfg, mesh, batch))
            return float(jax.jit(
                lambda q: loss_from_batch(cfg, q, b)[0])(p))

    ref = run(build_mesh(devices=jax.devices()[:1]), cfg)
    cfg2 = make_config("llama2", **common, tensor_model_parallel_size=2)
    got = run(build_mesh(tensor_model_parallel_size=2,
                         devices=jax.devices()[:2]), cfg2)
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_chunked_ce_under_pipeline():
    """ce_vocab_chunks applies in the pipelined head too (the default GPT
    head_loss_fn) — pp=2 GPipe loss matches pp=1 with chunks on."""
    from megatron_llm_tpu.core.parallel_state import build_mesh, global_mesh
    from megatron_llm_tpu.parallel.pipeline import pipeline_loss_fn

    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        vocab_size=256, seq_length=32, max_position_embeddings=64,
        params_dtype="float32", use_flash_attn=False, ce_vocab_chunks=4,
        pipeline_model_parallel_size=2, pipeline_schedule="gpipe",
    )
    cfg.parallel.num_micro_batches = 2
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    tok = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0, 256)
    batch = {"tokens": tok[:, :-1], "labels": tok[:, 1:],
             "loss_mask": jnp.ones((4, 32), jnp.float32)}

    cfg1 = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        vocab_size=256, seq_length=32, max_position_embeddings=64,
        params_dtype="float32", use_flash_attn=False, ce_vocab_chunks=4,
    )
    ref = float(jax.jit(lambda p: loss_from_batch(cfg1, p, batch)[0])(params))

    mesh = build_mesh(pipeline_model_parallel_size=2,
                      devices=jax.devices()[:2])
    with global_mesh(mesh):
        loss = float(jax.jit(
            lambda p: pipeline_loss_fn(cfg, mesh, p, batch, num_micro=2)[0]
        )(params))
    np.testing.assert_allclose(loss, ref, rtol=2e-5)
