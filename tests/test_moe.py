"""Mixture-of-Experts tests (models/moe.py) — beyond-reference feature.

The reference has no MoE (SURVEY §2.1: "EP absent"), so there is no reference
file to cite for parity; these tests follow the same discipline as the TP/CP
suites: exact semantics checks at small scale plus cross-mesh parity on the
8-device CPU mesh (conftest pins JAX_PLATFORMS=cpu with 8 virtual devices).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.core.parallel_state import build_mesh, global_mesh
from megatron_llm_tpu.models import init_model_params, make_config
from megatron_llm_tpu.models.language_model import loss_from_batch
from megatron_llm_tpu.models.moe import (
    init_moe_params,
    moe_capacity,
    moe_sublayer,
    route_tokens,
)


def tiny_cfg(**kw):
    defaults = dict(
        num_layers=2,
        hidden_size=64,
        num_attention_heads=4,
        num_attention_heads_kv=2,
        vocab_size=256,
        seq_length=32,
        max_position_embeddings=64,
        params_dtype="float32",
        micro_batch_size=2,
        global_batch_size=2,
        train_iters=5,
        use_flash_attn=False,
        num_experts=4,
        moe_router_topk=2,
    )
    defaults.update(kw)
    return make_config("mixtral", **defaults)


def make_batch(cfg, key, gbs=2):
    s = cfg.data.seq_length
    tok = jax.random.randint(key, (gbs, s + 1), 0, cfg.model.vocab_size)
    return {
        "tokens": tok[:, :-1],
        "labels": tok[:, 1:],
        "loss_mask": jnp.ones((gbs, s), jnp.float32),
    }


# ---------------------------------------------------------------------------
# routing semantics
# ---------------------------------------------------------------------------


def test_route_tokens_matches_naive_loop():
    """combine/dispatch must equal a per-token greedy seating by (slot, token)
    priority — the GShard convention the einsum formulation encodes."""
    cfg = tiny_cfg(num_experts=4, moe_router_topk=2, moe_capacity_factor=0.5)
    g_, t_, e_, k_ = 2, 16, 4, 2
    logits = jax.random.normal(jax.random.PRNGKey(0), (g_, t_, e_), jnp.float32)
    cap = moe_capacity(cfg, t_)
    combine, dispatch, aux = jax.jit(
        lambda l: route_tokens(cfg, l, cap)
    )(logits)
    combine = np.asarray(combine)

    probs = np.asarray(jax.nn.softmax(logits, -1))
    expected = np.zeros((g_, t_, e_, cap), np.float32)
    for g in range(g_):
        fill = np.zeros(e_, np.int64)
        # choices in priority order: all k=0 across tokens, then k=1
        topk = np.argsort(-probs[g], axis=-1)[:, :k_]  # [T, K]
        gates = np.take_along_axis(probs[g], topk, -1)
        gates = gates / gates.sum(-1, keepdims=True)  # normalize_gates
        for k in range(k_):
            for t in range(t_):
                e = topk[t, k]
                if fill[e] < cap:
                    expected[g, t, e, fill[e]] = gates[t, k]
                    fill[e] += 1
    np.testing.assert_allclose(combine, expected, rtol=1e-5, atol=1e-6)
    assert bool(jnp.all(dispatch == (combine > 0)))


def test_aux_loss_uniform_routing_is_one():
    """Switch load-balance loss equals 1.0 under perfectly uniform routing."""
    cfg = tiny_cfg(num_experts=8, moe_router_topk=2)
    logits = jnp.zeros((2, 64, 8), jnp.float32)
    _, _, aux = route_tokens(cfg, logits, capacity=64)
    np.testing.assert_allclose(float(aux[0]), 1.0, rtol=1e-5)
    # z-loss = mean(logsumexp(0..)^2) = log(8)^2
    np.testing.assert_allclose(float(aux[1]), np.log(8.0) ** 2, rtol=1e-5)


def test_capacity_drops_lowest_priority_tokens():
    cfg = tiny_cfg(num_experts=2, moe_router_topk=1, moe_capacity_factor=0.25,
                   moe_min_capacity=1)
    t_ = 16
    # all tokens prefer expert 0
    logits = jnp.tile(jnp.array([5.0, -5.0], jnp.float32), (1, t_, 1))
    cap = moe_capacity(cfg, t_)  # = max(1, ceil(16*0.25/2)) = 2
    combine, dispatch, _ = route_tokens(cfg, logits, cap)
    seated = np.asarray(dispatch.sum((2, 3)))[0]  # per-token
    assert seated[:cap].all() and not seated[cap:].any(), (
        "earlier tokens must win capacity"
    )


def test_single_expert_equals_dense_mlp():
    """E=1, k=1, ample capacity: MoE must reduce to the dense MLP with the
    same weights (gate = softmax over one logit = 1)."""
    from megatron_llm_tpu.models.transformer import mlp_sublayer

    # llama2 base: family validation allows E=1 (mixtral's requires >1)
    cfg = make_config(
        "llama2", hidden_size=64, num_attention_heads=4, vocab_size=256,
        num_experts=1, moe_router_topk=1, moe_capacity_factor=2.0,
        moe_min_capacity=64, params_dtype="float32",
    )
    key = jax.random.PRNGKey(0)
    p = init_moe_params(cfg, key)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 64), jnp.float32)
    out, aux = moe_sublayer(cfg, p, x)
    dense_p = jax.tree.map(lambda a: a[0], p["experts"])  # strip expert axis
    want = mlp_sublayer(cfg, dense_p, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# cross-mesh parity (ep / tp / dp compositions)
# ---------------------------------------------------------------------------


def _loss_and_grads(cfg, mesh, params, batch):
    from megatron_llm_tpu.parallel.tp import batch_shardings, param_shardings

    with global_mesh(mesh):
        ps = param_shardings(mesh, params)
        params = jax.device_put(params, ps)
        batch = jax.device_put(batch, batch_shardings(cfg, mesh, batch))

        def f(p, b):
            return loss_from_batch(cfg, p, b, deterministic=True)[0]

        loss, grads = jax.jit(jax.value_and_grad(f))(params, batch)
        return float(loss), jax.device_get(grads)


@pytest.mark.parametrize("layout", [
    dict(ep=2, tp=1, dp=2),
    dict(ep=2, tp=2, dp=2),
    dict(ep=4, tp=1, dp=4),
])
def test_ep_parity_with_single_device(layout):
    """Expert-parallel loss/grads must match the unsharded computation."""
    cfg = tiny_cfg()
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    batch = make_batch(cfg, jax.random.PRNGKey(1), gbs=4)

    ref_mesh = build_mesh(devices=jax.devices()[:1])
    ref_loss, ref_grads = _loss_and_grads(cfg, ref_mesh, params, batch)

    cfg2 = tiny_cfg()
    cfg2.parallel.expert_parallel_size = layout["ep"]
    cfg2.parallel.tensor_model_parallel_size = layout["tp"]
    cfg2.parallel.data_parallel_size = layout["dp"]
    mesh = build_mesh(
        tensor_model_parallel_size=layout["tp"],
        data_parallel_size=layout["dp"],
        expert_parallel_size=layout["ep"],
    )
    loss, grads = _loss_and_grads(cfg2, mesh, params, batch)

    np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)
    flat_ref = jax.tree_util.tree_leaves(ref_grads)
    flat = jax.tree_util.tree_leaves(grads)
    for a, b in zip(flat_ref, flat):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-4, atol=1e-5)


def test_moe_train_step_descends_with_ep():
    from megatron_llm_tpu.training_step import make_jitted_train_step

    cfg = tiny_cfg(global_batch_size=4)
    cfg.parallel.expert_parallel_size = 2
    cfg.parallel.tensor_model_parallel_size = 2
    cfg.parallel.data_parallel_size = 2
    cfg.optimizer.use_distributed_optimizer = True
    cfg.finalize()
    mesh = build_mesh(tensor_model_parallel_size=2, data_parallel_size=2,
                      expert_parallel_size=2)
    with global_mesh(mesh):
        params = init_model_params(cfg, jax.random.PRNGKey(0))
        step, _opt, sh = make_jitted_train_step(cfg, mesh, params)
        batch = sh["place_batch"](make_batch(cfg, jax.random.PRNGKey(1), gbs=4))
        o = sh["opt_state_value"]
        p = params
        losses = []
        for i in range(4):
            p, o, m = step(p, o, batch, i)
            losses.append(float(m["lm loss"]))
            assert np.isfinite(losses[-1])
            assert "moe aux loss" in m
        assert losses[-1] < losses[0]


def test_expert_param_shardings():
    """Expert stacks shard (ep, tp); router replicated; ZeRO-1 moments of
    expert weights keep their ep axis."""
    from jax.sharding import PartitionSpec as P

    from megatron_llm_tpu.optimizer.optimizer import (
        get_optimizer,
        opt_state_partition_specs,
    )
    from megatron_llm_tpu.parallel.tp import param_partition_specs

    cfg = tiny_cfg()
    cfg.optimizer.use_distributed_optimizer = True
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    specs = param_partition_specs(params)
    layers = specs["layers"]
    assert layers["moe"]["router"]["kernel"] == P("pp", None, None)
    assert layers["moe"]["experts"]["fc1"]["kernel"] == P("pp", "ep", None, None, "tp")
    assert layers["moe"]["experts"]["fc2"]["kernel"] == P("pp", "ep", "tp", None)

    opt = get_optimizer(cfg, params)
    state = opt.init(params)
    ospecs = opt_state_partition_specs(cfg, params, state, dp_size=2, ep_size=2)
    flat = jax.tree_util.tree_flatten_with_path(
        ospecs, is_leaf=lambda x: isinstance(x, P))[0]
    expert_moment_specs = [
        spec for path, spec in flat
        if "experts" in (names := tuple(
            getattr(k, "key", getattr(k, "name", str(k))) for k in path))
        and "fc1" in names and names[-1] == "kernel" and len(spec) >= 2
    ]
    # Adam has mu and nu subtrees, each mirroring the param tree
    assert len(expert_moment_specs) >= 2, (
        f"no expert-moment specs matched: {[p for p, _ in flat][:5]}..."
    )
    for spec in expert_moment_specs:
        assert spec[1] == "ep", f"expert moment lost ep sharding: {spec}"


def test_group_size_invariance_with_ample_capacity():
    """With capacity pressure absent, routing is per-token independent, so
    the grouped computation (moe_group_size < seq) must equal ungrouped."""
    cfg = tiny_cfg(moe_capacity_factor=8.0, moe_min_capacity=64)
    key = jax.random.PRNGKey(0)
    p = init_moe_params(cfg, key)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 64), jnp.float32)
    cfg.model.moe_group_size = 64
    out_full, _ = moe_sublayer(cfg, p, x)
    cfg.model.moe_group_size = 16
    out_grouped, _ = moe_sublayer(cfg, p, x)
    np.testing.assert_allclose(np.asarray(out_grouped), np.asarray(out_full),
                               rtol=2e-5, atol=2e-5)


def test_moe_kv_cached_decode_matches_full_forward():
    """Greedy KV-cached decode through MoE layers must match the full-context
    forward (the routing of a token must not depend on decode chunking)."""
    from megatron_llm_tpu.generation.generation import generate_tokens
    from megatron_llm_tpu.models import model_forward

    cfg = tiny_cfg(moe_capacity_factor=8.0, moe_min_capacity=64,
                   seq_length=48)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    total = 20
    tokens = np.zeros((1, total), np.int32)
    tokens[:, :8] = np.asarray(jax.random.randint(
        jax.random.PRNGKey(3), (1, 8), 0, cfg.model.vocab_size))
    out = generate_tokens(
        cfg, params, tokens, jnp.full((1,), 8, jnp.int32),
        jnp.int32(total), prefill_len=8,
        termination_id=cfg.model.vocab_size + 1,  # never fires
        sample_key=jax.random.PRNGKey(0), top_k=1,  # greedy
    )
    seq = out.tokens
    logits, _ = model_forward(cfg, params, seq[:, :-1])
    argmax = np.asarray(jnp.argmax(logits[..., :cfg.model.vocab_size], -1))
    gen = np.asarray(seq)
    for t in range(8, 20):
        assert gen[0, t] == argmax[0, t - 1], (
            f"decode diverges from teacher-forced argmax at {t}"
        )


def test_ep_with_context_parallel_parity():
    """MoE composed with ring-attention context parallelism: ep2 x cp2 x tp2
    loss matches the unsharded computation."""
    cfg = tiny_cfg(seq_length=64)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    batch = make_batch(cfg, jax.random.PRNGKey(1), gbs=2)

    ref_mesh = build_mesh(devices=jax.devices()[:1])
    ref_loss, _ = _loss_and_grads(cfg, ref_mesh, params, batch)

    cfg2 = tiny_cfg(seq_length=64)
    cfg2.parallel.expert_parallel_size = 2
    cfg2.parallel.tensor_model_parallel_size = 2
    cfg2.parallel.context_parallel_size = 2
    cfg2.parallel.data_parallel_size = 2
    mesh = build_mesh(
        tensor_model_parallel_size=2, context_parallel_size=2,
        data_parallel_size=2, expert_parallel_size=2,
    )
    loss, _ = _loss_and_grads(cfg2, mesh, params, batch)
    np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)


def test_moe_gpipe_pipeline_matches_unpipelined():
    """MoE under the GPipe schedule (pp=2): loss incl. the router aux term
    and grads (incl. router/expert grads through the aux loss) match the
    unsharded computation. Note the aux normalizations differ slightly by
    construction — the pipeline averages the per-microbatch balance loss
    (matching the pp=1 grad-accumulation mean) while the reference here
    computes it over the full batch; with coeff 0.01 the gap is ~1e-5 and
    sits inside the tolerance."""
    from megatron_llm_tpu.parallel.pipeline import pipeline_loss_fn

    cfg = tiny_cfg(seq_length=32, global_batch_size=4)
    cfg.parallel.pipeline_model_parallel_size = 2
    cfg.parallel.pipeline_schedule = "gpipe"
    cfg.parallel.num_micro_batches = 2
    cfg.finalize()
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    batch = make_batch(cfg, jax.random.PRNGKey(1), gbs=4)

    cfg1 = tiny_cfg(seq_length=32, global_batch_size=4)
    ref_loss, ref_grads = jax.jit(jax.value_and_grad(
        lambda p: loss_from_batch(cfg1, p, batch, deterministic=True)[0]
    ))(params)

    mesh = build_mesh(pipeline_model_parallel_size=2,
                      devices=jax.devices()[:2])
    with global_mesh(mesh):
        loss, grads = jax.jit(jax.value_and_grad(
            lambda p: pipeline_loss_fn(cfg, mesh, p, batch, num_micro=2)[0]
        ))(params)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(ref_grads)[0],
        jax.tree_util.tree_flatten_with_path(grads)[0],
    ):
        # same tolerance as the dense GPipe parity suite (test_pipeline.py):
        # the scan-transpose backward reorders fp32 accumulations
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=5e-4, atol=5e-4,
            err_msg=f"grad mismatch at {pa}",
        )


def test_moe_interleaved_gpipe_pipeline_matches_unpipelined():
    """MoE + virtual-pipeline GPipe (pp=2, vpp=2): the per-chunk aux
    accumulation must still count every layer exactly once per microbatch."""
    from megatron_llm_tpu.parallel.pipeline import pipeline_loss_fn

    cfg = tiny_cfg(seq_length=32, global_batch_size=4, num_layers=4)
    cfg.parallel.pipeline_model_parallel_size = 2
    cfg.parallel.pipeline_schedule = "gpipe"
    cfg.parallel.virtual_pipeline_model_parallel_size = 2
    cfg.parallel.num_micro_batches = 2
    cfg.finalize()
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    batch = make_batch(cfg, jax.random.PRNGKey(1), gbs=4)

    cfg1 = tiny_cfg(seq_length=32, global_batch_size=4, num_layers=4)
    ref_loss = float(jax.jit(
        lambda p: loss_from_batch(cfg1, p, batch, deterministic=True)[0]
    )(params))

    mesh = build_mesh(pipeline_model_parallel_size=2,
                      devices=jax.devices()[:2])
    with global_mesh(mesh):
        loss, mets = jax.jit(
            lambda p: pipeline_loss_fn(cfg, mesh, p, batch, num_micro=2)
        )(params)
    np.testing.assert_allclose(float(loss), ref_loss, rtol=2e-5)
    assert np.isfinite(float(mets["moe aux loss"]))


def _moe_1f1b_parity(vpp, num_layers):
    """MoE under the true-1F1B schedules (round-3 VERDICT item 3): the
    router aux term enters the loss and its gradient reaches the router
    and expert weights via the per-stage vjp aux seed — parity with the
    unpipelined computation, mirroring test_pipeline.py's dense suite."""
    from megatron_llm_tpu.parallel.pipeline import (
        pipeline_1f1b_interleaved_loss_and_grads,
        pipeline_1f1b_loss_and_grads,
    )

    cfg = tiny_cfg(seq_length=32, global_batch_size=4, num_layers=num_layers)
    cfg.parallel.pipeline_model_parallel_size = 2
    cfg.parallel.pipeline_schedule = "1f1b"
    if vpp > 1:
        cfg.parallel.virtual_pipeline_model_parallel_size = vpp
    cfg.parallel.num_micro_batches = 4
    cfg.finalize()
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    batch = make_batch(cfg, jax.random.PRNGKey(1), gbs=4)

    cfg1 = tiny_cfg(seq_length=32, global_batch_size=4,
                    num_layers=num_layers)
    cfg1.parallel.num_micro_batches = 4
    ref_loss, ref_grads = jax.jit(jax.value_and_grad(
        lambda p: loss_from_batch(cfg1, p, batch, deterministic=True)[0]
    ))(params)

    engine = (pipeline_1f1b_interleaved_loss_and_grads if vpp > 1
              else pipeline_1f1b_loss_and_grads)
    mesh = build_mesh(pipeline_model_parallel_size=2,
                      devices=jax.devices()[:2])
    with global_mesh(mesh):
        loss, grads = jax.jit(
            lambda p: engine(cfg, mesh, p, batch, num_micro=4)
        )(params)

    # the aux normalization gap vs the full-batch reference is ~coeff*1e-3
    # (same situation as the GPipe parity test's docstring)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-4)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(ref_grads)[0],
        jax.tree_util.tree_flatten_with_path(grads)[0],
    ):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=5e-4, atol=5e-4,
            err_msg=f"grad mismatch at {pa}",
        )


def test_moe_1f1b_pipeline_matches_unpipelined():
    _moe_1f1b_parity(vpp=1, num_layers=2)


def test_moe_interleaved_1f1b_pipeline_matches_unpipelined():
    _moe_1f1b_parity(vpp=2, num_layers=4)


def test_expert_choice_routing_is_balanced():
    """EC routing: every expert fills exactly C slots with its top-C tokens
    by affinity (Zhou et al. 2022) — balanced by construction."""
    from megatron_llm_tpu.models.moe import route_expert_choice

    cfg = tiny_cfg(moe_router_type="expert_choice")
    g_, t_, e_, cap = 2, 16, 4, 4
    logits = jax.random.normal(jax.random.PRNGKey(0), (g_, t_, e_))
    combine, dispatch, aux = route_expert_choice(cfg, logits, cap)
    # each (expert, slot) seats exactly one token
    np.testing.assert_array_equal(
        np.asarray(dispatch.sum(1)), np.ones((g_, e_, cap)))
    # seated tokens are the top-C by affinity
    probs = np.asarray(jax.nn.softmax(logits, -1))
    for g in range(g_):
        for e in range(e_):
            seated = set(np.where(np.asarray(dispatch)[g, :, e].any(-1))[0])
            want = set(np.argsort(-probs[g, :, e])[:cap])
            assert seated == want
    # aux[0] reports EC's health signal: the dropped-token fraction
    # (tokens selected by NO expert) — metric-only, never enters the loss
    # (aux_loss_coeffs zeroes the balance coefficient for expert_choice)
    covered = np.asarray(dispatch).any(axis=(2, 3))  # [G, T]
    expected_dropped = 1.0 - covered.mean()
    np.testing.assert_allclose(float(aux[0]), expected_dropped, rtol=1e-6)
    assert 0.0 <= float(aux[0]) < 1.0


def test_expert_choice_capacity_clamps_to_group():
    """EC capacity never exceeds tokens-per-group (top_k would reject k > T):
    few-expert configs and s=1 decode groups must not crash."""
    from megatron_llm_tpu.models.moe import (
        init_moe_params,
        moe_capacity_expert_choice,
    )

    cfg = tiny_cfg(num_experts=2, moe_router_topk=1,
                   moe_router_type="expert_choice", moe_capacity_factor=4.0)
    assert moe_capacity_expert_choice(cfg, 16) == 16  # ceil(16*4/2)=32 -> 16
    assert moe_capacity_expert_choice(cfg, 1) == 1    # decode: one token
    p = init_moe_params(cfg, jax.random.PRNGKey(0))
    out, _ = moe_sublayer(cfg, p, jax.random.normal(
        jax.random.PRNGKey(1), (2, 1, cfg.model.hidden_size)))
    assert out.shape == (2, 1, cfg.model.hidden_size)


def test_expert_choice_balance_term_not_in_loss():
    """EC's constant balance metric must not offset the trained loss."""
    from megatron_llm_tpu.models.moe import aux_loss_coeffs

    cfg = tiny_cfg(moe_router_type="expert_choice")
    assert aux_loss_coeffs(cfg)[0] == 0.0
    cfg2 = tiny_cfg()
    assert aux_loss_coeffs(cfg2)[0] == cfg2.model.moe_aux_loss_coeff


def test_expert_choice_model_trains():
    cfg = tiny_cfg(moe_router_type="expert_choice", global_batch_size=2)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    batch = make_batch(cfg, jax.random.PRNGKey(1), gbs=2)

    @jax.jit
    def step(p):
        loss, g = jax.value_and_grad(
            lambda q: loss_from_batch(cfg, q, batch, deterministic=True)[0]
        )(p)
        return loss, jax.tree.map(lambda w, gg: w - 0.3 * gg, p, g)

    p = params
    losses = []
    for _ in range(15):
        loss, p = step(p)
        losses.append(float(loss))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_moe_checkpoint_reshard_round_trip(tmp_path):
    """Mixtral checkpoints reshard through tools/checkpoint_util (expert
    stacks are plain pytree leaves with generic sharding rules, so the
    vocab-repad + parallel-config rewrite must pass them through intact)."""
    import sys
    from pathlib import Path

    import orbax.checkpoint as ocp

    sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))
    from checkpoint_util import reshard_checkpoint

    from megatron_llm_tpu.checkpointing import save_checkpoint

    cfg = tiny_cfg()
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    save_checkpoint(cfg, str(tmp_path / "src"), 3, params)
    meta = reshard_checkpoint(str(tmp_path / "src"), str(tmp_path / "dst"),
                              target_tp=2, target_pp=1)
    assert meta["config"]["parallel"]["tensor_model_parallel_size"] == 2
    restored = ocp.StandardCheckpointer().restore(
        str(tmp_path / "dst" / "iter_0000003" / "params"))
    np.testing.assert_array_equal(
        np.asarray(restored["layers"]["moe"]["experts"]["fc1"]["kernel"]),
        np.asarray(params["layers"]["moe"]["experts"]["fc1"]["kernel"]))


def test_moe_generation_server_roundtrip():
    """The REST server generates from a Mixtral-family model (KV-cached MoE
    decode behind the full serving stack)."""
    from megatron_llm_tpu.generation import InferenceEngine
    from megatron_llm_tpu.generation.server import MegatronServer

    class ToyTok:
        eod = 0
        bos = 1

        @property
        def vocab_size(self):
            return 64

        def tokenize(self, text):
            return [2 + (ord(c) % 62) for c in text]

        def detokenize(self, ids):
            return "".join(chr(97 + (i % 26)) for i in ids if i >= 2)

    cfg = tiny_cfg(vocab_size=64, seq_length=64, moe_capacity_factor=8.0,
                   moe_min_capacity=64)
    cfg.inference.max_tokens_to_oom = 256
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    server = MegatronServer(InferenceEngine(cfg, params, ToyTok()))
    status, body = server.handle_request(
        {"prompts": ["hello moe"], "tokens_to_generate": 8}
    )
    assert status == 200, body
    assert len(body["text"]) == 1 and isinstance(body["text"][0], str)


def test_moe_rejects_encoder_families():
    with pytest.raises(AssertionError):
        make_config("bert", vocab_size=256, num_experts=4)


def test_mixtral_family_config():
    cfg = make_config("mixtral", vocab_size=256)
    assert cfg.model.num_experts == 8
    assert cfg.model.moe_router_topk == 2
    # finalize rejects ep>1 without MoE
    with pytest.raises(AssertionError):
        make_config("llama2", vocab_size=256, expert_parallel_size=2)
