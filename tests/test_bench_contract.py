"""bench.py evidence contract (VERDICT round-2 item 1).

Off-TPU the headline fields must report 0 (a CPU step time over a nominal
peak is not an MFU measurement); successful TPU measurements persist to
timestamped evidence files the fallback line carries; sweeps never clobber
the headline record; tpu_watch only counts a job as captured when its
output proves it ran on hardware.
"""

from __future__ import annotations

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from tools.tpu_watch import _bench_on_tpu, _kernel_check_on_tpu  # noqa: E402


@pytest.fixture()
def evidence_dir(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "LAST_TPU_PATH",
                        str(tmp_path / "BENCH_LAST_TPU.json"))
    return tmp_path


def test_metric_name_carries_seq():
    assert bench.metric_name(1024) == bench.METRIC
    assert "seq32768" in bench.metric_name(32768)


def test_cpu_contract_zeroes_headline(evidence_dir):
    line = bench.cpu_contract_line({
        "metric": bench.METRIC, "value": 6.75, "unit": "%MFU",
        "vs_baseline": 0.577, "backend": "cpu", "loss": 7.3,
        "tokens_per_sec": 111.0})
    assert line["value"] == 0.0 and line["vs_baseline"] == 0.0
    assert line["cpu_sanity"]["tokens_per_sec"] == 111.0
    assert "value" not in line["cpu_sanity"]
    # unit preserved for non-default metrics (moe_bench)
    moe = bench.cpu_contract_line({"metric": "m", "value": 5.0,
                                   "unit": "%MFU(active)", "backend": "cpu"})
    assert moe["unit"] == "%MFU(active)" and "vs_baseline" not in moe


def test_persistence_routing(evidence_dir):
    stock = {"metric": bench.METRIC, "value": 40.0, "backend": "tpu"}
    bench.persist_tpu_result(stock, {"seq": 1024, "mbs": 16}, stock=True)
    rec = bench.load_last_tpu()
    assert rec["value"] == 40.0 and "timestamp_utc" in rec
    assert rec["invocation"]["mbs"] == 16

    # a sweep at seq 1024 must NOT clobber the headline evidence
    bench.persist_tpu_result({"metric": bench.METRIC, "value": 1.0,
                              "backend": "tpu"}, {"seq": 1024}, stock=False)
    assert bench.load_last_tpu()["value"] == 40.0
    assert os.path.exists(str(evidence_dir / "BENCH_LAST_TPU_sweep.json"))

    # long-context rows go to their own per-seq file
    bench.persist_tpu_result({"metric": bench.metric_name(32768),
                              "value": 9.0, "backend": "tpu"},
                             {"seq": 32768})
    assert bench.load_last_tpu(32768)["value"] == 9.0
    assert bench.load_last_tpu()["value"] == 40.0

    # tagged evidence (moe_bench)
    bench.persist_tpu_result({"metric": "moe", "value": 25.0,
                              "backend": "tpu"}, {"seq": 1024}, tag="moe8x2")
    assert os.path.exists(str(evidence_dir / "BENCH_LAST_TPU_moe8x2.json"))


def test_attach_prefers_matching_seq(evidence_dir):
    bench.persist_tpu_result({"metric": bench.METRIC, "value": 40.0,
                              "backend": "tpu"}, {"seq": 1024}, stock=True)
    line = bench.attach_last_tpu({"metric": "m"}, 32768)
    assert line["last_measured_tpu"]["value"] == 40.0  # headline fallback
    bench.persist_tpu_result({"metric": bench.metric_name(32768),
                              "value": 9.0, "backend": "tpu"}, {"seq": 32768})
    line = bench.attach_last_tpu({"metric": "m"}, 32768)
    assert line["last_measured_tpu"]["value"] == 9.0  # per-seq preferred


def test_watch_predicates():
    assert _bench_on_tpu(json.dumps({"metric": "m", "backend": "tpu"}))
    assert not _bench_on_tpu(json.dumps({"metric": "m", "backend": "cpu"}))
    assert not _bench_on_tpu("no json here")
    # error lines carry no backend field -> not evidence
    assert not _bench_on_tpu(json.dumps({"metric": "m", "value": 0.0,
                                         "error": "watchdog"}))
    assert _kernel_check_on_tpu("backend: tpu (TPU v5 lite)\nPASS x\n" + "y" * 3000)
    assert not _kernel_check_on_tpu("backend: cpu (cpu)\nnot on TPU")


def test_decode_bench_in_watch_jobs():
    """VERDICT round-3 item 5: the decode bench is part of the tunnel-up
    capture list, with the bench-style (no subprocess timeout — it carries
    its own watchdog) + TPU-evidence-predicate contract."""
    from tools.tpu_watch import JOBS

    by_name = {name: (cmd, bounded, pred) for name, cmd, bounded, pred in JOBS}
    assert "decode_bench" in by_name
    cmd, bounded, pred = by_name["decode_bench"]
    assert cmd[-1].endswith("decode_bench.py")
    assert bounded is False and pred is _bench_on_tpu


def test_decode_bench_cpu_contract(evidence_dir):
    """The decode tool reuses bench.py's off-TPU contract: headline 0,
    run rides under cpu_sanity, tagged evidence file when on TPU."""
    line = bench.cpu_contract_line({
        "metric": "decode_tok_s_llama470m_b8_p128_g128_1chip",
        "value": 1234.5, "unit": "tok/s", "backend": "cpu",
        "rows": [{"batch": 8, "decode_tok_s": 1234.5}]}, tag="decode")
    assert line["value"] == 0.0 and line["unit"] == "tok/s"
    assert line["cpu_sanity"]["rows"][0]["decode_tok_s"] == 1234.5
    # tagged TPU persistence routes to its own evidence file
    bench.persist_tpu_result({"metric": "decode", "value": 999.0,
                              "backend": "tpu"}, {}, tag="decode")
    assert bench.load_last_tpu(tag="decode")["value"] == 999.0
    assert bench.load_last_tpu() is None  # headline untouched


def test_engine_decode_bench_cpu_contract(evidence_dir):
    """bench_decode.py (ISSUE 1) reuses bench.py's off-TPU contract:
    headline 0, the occupancy sweep + speedup ride under cpu_sanity, TPU
    evidence goes to its own tagged file."""
    line = bench.cpu_contract_line({
        "metric": "engine_decode_tok_s_llama470m_c8_1chip",
        "value": 2285.1, "unit": "tok/s", "backend": "cpu",
        "speedup_vs_sequential": 5.48,
        "rows": [{"concurrency": 8, "engine_tok_s": 2285.1,
                  "tick_ms": 3.5, "speedup_vs_sequential": 5.48}],
    }, tag="engine_decode")
    assert line["value"] == 0.0 and line["unit"] == "tok/s"
    assert line["cpu_sanity"]["speedup_vs_sequential"] == 5.48
    assert line["cpu_sanity"]["rows"][0]["tick_ms"] == 3.5
    bench.persist_tpu_result({"metric": "engine_decode", "value": 9000.0,
                              "backend": "tpu"}, {}, tag="engine_decode")
    assert bench.load_last_tpu(tag="engine_decode")["value"] == 9000.0
    assert bench.load_last_tpu() is None  # headline untouched


def test_engine_decode_bench_in_watch_jobs():
    """The engine decode bench is in the tunnel-up capture list with the
    bench-style contract (own watchdog, bench evidence predicate)."""
    from tools.tpu_watch import JOBS

    by_name = {name: (cmd, bounded, pred) for name, cmd, bounded, pred in JOBS}
    assert "engine_decode_bench" in by_name
    cmd, bounded, pred = by_name["engine_decode_bench"]
    assert cmd[-1].endswith("bench_decode.py")
    assert bounded is False and pred is _bench_on_tpu


def test_prefix_bench_cpu_contract(evidence_dir):
    """bench_decode.py --mode shared_prefix (ISSUE 5) reuses bench.py's
    off-TPU contract: headline 0, the cache-on/off comparison (prefill
    tokens, TTFT, hit rate) rides under cpu_sanity, TPU evidence goes to
    its own tagged file."""
    line = bench.cpu_contract_line({
        "metric": "engine_prefix_prefill_reduction_llama470m_c8_1chip",
        "value": 7.0, "unit": "x", "backend": "cpu",
        "ttft_mean_speedup": 1.7, "hit_rate": 0.92,
        "rows": [{"concurrency": 8, "prefill_token_reduction": 7.0,
                  "reduction_ok": True,
                  "cache_on": {"prefill_tokens_computed": 128},
                  "cache_off": {"prefill_tokens_computed": 896}}],
    }, tag="engine_decode_prefix")
    assert line["value"] == 0.0 and line["unit"] == "x"
    assert line["cpu_sanity"]["ttft_mean_speedup"] == 1.7
    assert line["cpu_sanity"]["rows"][0]["reduction_ok"] is True
    bench.persist_tpu_result({"metric": "engine_prefix", "value": 4.2,
                              "backend": "tpu"}, {},
                             tag="engine_decode_prefix")
    assert bench.load_last_tpu(tag="engine_decode_prefix")["value"] == 4.2
    assert bench.load_last_tpu() is None  # headline untouched


def test_prefix_bench_in_watch_jobs():
    """ISSUE 5: the shared-prefix decode bench is in the tunnel-up capture
    list (own watchdog, bench evidence predicate)."""
    from tools.tpu_watch import JOBS

    by_name = {name: (cmd, bounded, pred) for name, cmd, bounded, pred in JOBS}
    assert "bench_decode_prefix" in by_name
    cmd, bounded, pred = by_name["bench_decode_prefix"]
    assert "--mode" in cmd and "shared_prefix" in cmd
    assert bounded is False and pred is _bench_on_tpu


def test_slo_bench_cpu_contract(evidence_dir):
    """bench_decode.py --mode slo (ISSUE 7) reuses bench.py's off-TPU
    contract: headline 0, the per-policy TTFT/deadline-miss/preemption
    comparison rides under cpu_sanity WITH the host-cost budget fields
    populated, TPU evidence goes to its own tagged file."""
    line = bench.cpu_contract_line({
        "metric": "engine_slo_hi_p99_ttft_speedup_llama470m_1chip",
        "value": 3.1, "unit": "x", "backend": "cpu",
        "speedup_ok": True,
        "hi_deadline_miss_rate": {"fcfs": 1.0, "slo": 0.0},
        "preemptions": {"fcfs": 0, "slo": 2},
        "compile_time_s": 2.7, "step_time_s": 0.002,
        "rows": [{"policy": "fcfs", "hi": {"ttft_p99_ms": 359.0}},
                 {"policy": "slo", "hi": {"ttft_p99_ms": 115.0}}],
    }, tag="engine_decode_slo")
    assert line["value"] == 0.0 and line["unit"] == "x"
    assert line["cpu_sanity"]["speedup_ok"] is True
    assert line["cpu_sanity"]["hi_deadline_miss_rate"]["slo"] == 0.0
    assert line["cpu_sanity"]["preemptions"]["slo"] == 2
    # budget fields populated and within caps (no error stamp)
    assert line["budgets"]["compile_time_s"]["value"] == 2.7
    assert line["budgets"]["step_time_s"]["budget"] == 120.0
    assert "error" not in line
    bench.persist_tpu_result({"metric": "engine_slo", "value": 2.5,
                              "backend": "tpu"}, {},
                             tag="engine_decode_slo")
    assert bench.load_last_tpu(tag="engine_decode_slo")["value"] == 2.5
    assert bench.load_last_tpu() is None  # headline untouched


def test_slo_bench_in_watch_jobs():
    """ISSUE 7: the scheduling-policy overload bench is in the tunnel-up
    capture list (own watchdog, bench evidence predicate)."""
    from tools.tpu_watch import JOBS

    by_name = {name: (cmd, bounded, pred) for name, cmd, bounded, pred in JOBS}
    assert "bench_decode_slo" in by_name
    cmd, bounded, pred = by_name["bench_decode_slo"]
    assert "--mode" in cmd and "slo" in cmd
    assert bounded is False and pred is _bench_on_tpu


def test_committed_slo_evidence_is_valid():
    """The committed CPU-sanity evidence (BENCH_decode_slo_cpu_sanity.json)
    satisfies the contract: headline 0 off-TPU, >= 2x hi-priority p99
    TTFT for slo vs fcfs, miss rates + preemptions present, budgets
    populated without violations."""
    import json
    from pathlib import Path

    path = Path(__file__).parent.parent / "BENCH_decode_slo_cpu_sanity.json"
    rec = json.loads(path.read_text())
    assert rec["value"] == 0.0 and rec["backend"] == "cpu"
    sanity = rec["cpu_sanity"]
    assert sanity["speedup_ok"] is True
    by = {r["policy"]: r for r in sanity["rows"]}
    assert set(by) == {"fcfs", "priority", "slo"}
    assert (by["fcfs"]["hi"]["ttft_p99_ms"]
            >= 2.0 * by["slo"]["hi"]["ttft_p99_ms"])
    assert by["slo"]["preemptions"] >= 1
    for row in by.values():
        assert {"ttft_p50_ms", "ttft_p99_ms",
                "deadline_miss_rate"} <= set(row["hi"])
    assert "compile_time_s" in rec["budgets"]
    assert "error" not in rec


def test_spec_bench_cpu_contract(evidence_dir):
    """bench_decode.py --mode spec (ISSUE 9) reuses the off-TPU contract:
    headline 0, the spec-on/off comparison + acceptance rate ride under
    cpu_sanity with the budget fields populated."""
    line = bench.cpu_contract_line({
        "metric": "engine_spec_decode_speedup_llama470m_c1_1chip",
        "value": 1.7, "unit": "x", "backend": "cpu",
        "speedup_ok": True, "acceptance_rate": 1.0, "spec_k": 4,
        "compile_time_s": 5.0, "step_time_s": 0.013,
        "rows": [{"concurrency": 1, "speedup": 1.7,
                  "on": {"decode_tok_s": 350.0, "acceptance_rate": 1.0},
                  "off": {"decode_tok_s": 206.0}}],
    }, tag="engine_decode_spec")
    assert line["value"] == 0.0 and line["unit"] == "x"
    assert line["cpu_sanity"]["speedup_ok"] is True
    assert line["cpu_sanity"]["acceptance_rate"] == 1.0
    assert line["budgets"]["compile_time_s"]["value"] == 5.0
    assert "error" not in line
    bench.persist_tpu_result({"metric": "engine_spec", "value": 2.1,
                              "backend": "tpu"}, {},
                             tag="engine_decode_spec")
    assert bench.load_last_tpu(tag="engine_decode_spec")["value"] == 2.1
    assert bench.load_last_tpu() is None  # headline untouched


def test_spec_bench_in_watch_jobs():
    """ISSUE 9: the speculative-decoding bench is in the tunnel-up capture
    list (own watchdog, bench evidence predicate)."""
    from tools.tpu_watch import JOBS

    by_name = {name: (cmd, bounded, pred) for name, cmd, bounded, pred in JOBS}
    assert "bench_decode_spec" in by_name
    cmd, bounded, pred = by_name["bench_decode_spec"]
    assert "--mode" in cmd and "spec" in cmd
    assert bounded is False and pred is _bench_on_tpu


def test_committed_spec_evidence_is_valid():
    """The committed CPU-sanity evidence (BENCH_decode_spec_cpu_sanity.json)
    satisfies the contract: headline 0 off-TPU, >= 1.3x decode tok/s at
    concurrency 1 with the acceptance rate alongside, budgets populated,
    and the line is one an error-rejecting watch predicate accepts."""
    import json as _json
    from pathlib import Path

    path = Path(__file__).parent.parent / "BENCH_decode_spec_cpu_sanity.json"
    rec = _json.loads(path.read_text())
    assert rec["value"] == 0.0 and rec["backend"] == "cpu"
    sanity = rec["cpu_sanity"]
    assert sanity["speedup_ok"] is True
    assert sanity["acceptance_rate"] is not None
    by_c = {r["concurrency"]: r for r in sanity["rows"]}
    assert by_c[1]["speedup"] >= 1.3
    for row in by_c.values():
        assert {"decode_tok_s", "latency_p50_ms",
                "latency_p99_ms"} <= set(row["on"])
        assert "acceptance_rate" in row["on"]
    assert "compile_time_s" in rec["budgets"]
    assert "error" not in rec
    # the watch predicate's contract: an error-stamped line of this very
    # shape must be rejected (not captured as evidence)
    stamped = dict(rec)
    stamped["error"] = "watchdog: engine decode bench exceeded 1500s"
    assert not _bench_on_tpu(json.dumps(stamped))


def test_router_bench_cpu_contract(evidence_dir):
    """bench_decode.py --mode router (ISSUE 10) reuses the off-TPU
    contract: headline 0, the prefix_affinity-vs-round_robin comparison +
    failover record ride under cpu_sanity with the budget fields
    populated, TPU evidence goes to its own tagged file."""
    line = bench.cpu_contract_line({
        "metric": "router_prefix_affinity_ttft_speedup_llama470m_2rep_1chip",
        "value": 1.3, "unit": "x", "backend": "cpu",
        "speedup_ok": True, "fleet_hit_rate_gain": 0.23,
        "failover": {"killed": "http://127.0.0.1:1", "requests": 12,
                     "dropped": 0, "failovers": 2,
                     "killed_state": "ejected", "ok": True},
        "compile_time_s": 40.0, "step_time_s": 0.02,
        "rows": [{"policy": "round_robin", "fleet_hit_rate": 0.75,
                  "ttft_mean_ms": 369.0},
                 {"policy": "prefix_affinity", "fleet_hit_rate": 0.98,
                  "ttft_mean_ms": 328.0}],
    }, tag="engine_decode_router")
    assert line["value"] == 0.0 and line["unit"] == "x"
    assert line["cpu_sanity"]["speedup_ok"] is True
    assert line["cpu_sanity"]["failover"]["dropped"] == 0
    assert line["budgets"]["compile_time_s"]["value"] == 40.0
    assert "error" not in line
    bench.persist_tpu_result({"metric": "router", "value": 1.8,
                              "backend": "tpu"}, {},
                             tag="engine_decode_router")
    assert bench.load_last_tpu(tag="engine_decode_router")["value"] == 1.8
    assert bench.load_last_tpu() is None  # headline untouched


def test_router_bench_in_watch_jobs():
    """ISSUE 10: the cross-replica router bench is in the tunnel-up
    capture list (own watchdog, bench evidence predicate)."""
    from tools.tpu_watch import JOBS

    by_name = {name: (cmd, bounded, pred) for name, cmd, bounded, pred in JOBS}
    assert "bench_decode_router" in by_name
    cmd, bounded, pred = by_name["bench_decode_router"]
    assert "--mode" in cmd and "router" in cmd
    assert bounded is False and pred is _bench_on_tpu


def test_committed_router_evidence_is_valid():
    """The committed CPU-sanity evidence (BENCH_decode_router_cpu_sanity
    .json) satisfies the acceptance bar: headline 0 off-TPU,
    prefix_affinity beats round_robin on BOTH fleet prefix-hit rate and
    mean TTFT, the mid-run kill dropped nothing and ejected the dead
    replica, budgets populated without violations."""
    from pathlib import Path

    path = (Path(__file__).parent.parent
            / "BENCH_decode_router_cpu_sanity.json")
    rec = json.loads(path.read_text())
    assert rec["value"] == 0.0 and rec["backend"] == "cpu"
    sanity = rec["cpu_sanity"]
    assert sanity["speedup_ok"] is True
    by = {r["policy"]: r for r in sanity["rows"]}
    assert set(by) == {"round_robin", "prefix_affinity"}
    aff, rr = by["prefix_affinity"], by["round_robin"]
    assert aff["fleet_hit_rate"] > rr["fleet_hit_rate"]
    assert aff["ttft_mean_ms"] < rr["ttft_mean_ms"]
    assert aff["prefill_tokens_computed"] < rr["prefill_tokens_computed"]
    fo = sanity["failover"]
    assert fo["dropped"] == 0 and fo["ok"] is True
    assert fo["failovers"] >= 1
    assert fo["killed_state"] in ("suspect", "ejected")
    assert "compile_time_s" in rec["budgets"]
    assert "error" not in rec
    # an error-stamped line of this shape must be rejected by the watch
    # evidence predicate, not captured
    stamped = dict(rec)
    stamped["error"] = "watchdog: engine decode bench exceeded 1500s"
    assert not _bench_on_tpu(json.dumps(stamped))


def test_mixed_bench_cpu_contract(evidence_dir):
    """bench_decode.py --mode mixed (ISSUE 11) reuses the off-TPU
    contract: headline 0, the ragged-vs-legacy comparison rides under
    cpu_sanity with budget fields populated, TPU evidence goes to its
    own tagged file."""
    line = bench.cpu_contract_line({
        "metric": "engine_ragged_launch_reduction_llama470m_mixed_1chip",
        "value": 2.1, "unit": "x", "backend": "cpu",
        "speedup_ok": True, "ttft_speedup": 1.12, "tok_s_speedup": 1.05,
        "compile_time_s": 50.0, "step_time_s": 0.03,
        "rows": [{"ragged": False, "launches_per_tick": 2.1,
                  "long_ttft_mean_ms": 900.0, "decode_tok_s": 40.0},
                 {"ragged": True, "launches_per_tick": 1.0,
                  "long_ttft_mean_ms": 800.0, "decode_tok_s": 42.0}],
    }, tag="engine_decode_mixed")
    assert line["value"] == 0.0 and line["unit"] == "x"
    assert line["cpu_sanity"]["speedup_ok"] is True
    assert line["budgets"]["compile_time_s"]["value"] == 50.0
    assert "error" not in line
    bench.persist_tpu_result({"metric": "engine_mixed", "value": 2.2,
                              "backend": "tpu"}, {},
                             tag="engine_decode_mixed")
    assert bench.load_last_tpu(tag="engine_decode_mixed")["value"] == 2.2
    assert bench.load_last_tpu() is None  # headline untouched


def test_mixed_bench_in_watch_jobs():
    """ISSUE 11: the ragged mixed-workload bench is in the tunnel-up
    capture list (own watchdog, bench evidence predicate)."""
    from tools.tpu_watch import JOBS

    by_name = {name: (cmd, bounded, pred) for name, cmd, bounded, pred in JOBS}
    assert "bench_decode_mixed" in by_name
    cmd, bounded, pred = by_name["bench_decode_mixed"]
    assert "--mode" in cmd and "mixed" in cmd
    assert bounded is False and pred is _bench_on_tpu


def test_committed_mixed_evidence_is_valid():
    """The committed CPU-sanity evidence (BENCH_decode_mixed_cpu_sanity
    .json) satisfies the acceptance bar: headline 0 off-TPU, the ragged
    arm runs exactly ONE attention launch per tick with >= 1.5x fewer
    launches than the legacy split dispatch, TTFT/tok-s no worse, and
    budgets populated without violations."""
    from pathlib import Path

    path = (Path(__file__).parent.parent
            / "BENCH_decode_mixed_cpu_sanity.json")
    rec = json.loads(path.read_text())
    assert rec["value"] == 0.0 and rec["backend"] == "cpu"
    sanity = rec["cpu_sanity"]
    assert sanity["speedup_ok"] is True
    assert sanity["launch_reduction"] >= 1.5
    by = {r["ragged"]: r for r in sanity["rows"]}
    assert set(by) == {True, False}
    assert by[True]["launches_per_tick"] <= 1.001
    assert (by[False]["launches_per_tick"]
            >= 1.5 * by[True]["launches_per_tick"])
    assert sanity["ttft_speedup"] >= 0.95
    assert sanity["tok_s_speedup"] >= 0.95
    assert "compile_time_s" in rec["budgets"]
    assert "error" not in rec
    # an error-stamped line of this shape must be rejected by the watch
    # evidence predicate, not captured
    stamped = dict(rec)
    stamped["error"] = "watchdog: engine decode bench exceeded 1500s"
    assert not _bench_on_tpu(json.dumps(stamped))


# ---------------------------------------------------------------------------
# ISSUE 13: quantized-KV capacity bench
# ---------------------------------------------------------------------------


def test_capacity_bench_cpu_contract(evidence_dir):
    """bench_decode.py --mode capacity (ISSUE 13) reuses the off-TPU
    contract: headline 0, the fixed-byte-budget int8-vs-bf16 comparison
    rides under cpu_sanity with budget fields populated, TPU evidence
    goes to its own tagged file."""
    line = bench.cpu_contract_line({
        "metric": "engine_kv_capacity_slot_ratio_llama470m_1chip",
        "value": 2.3, "unit": "x", "backend": "cpu",
        "capacity_ok": True, "greedy_match": True, "slot_ratio": 2.3,
        "hit_rate_bf16": 0.44, "hit_rate_int8": 0.89,
        "compile_time_s": 3.0, "step_time_s": 0.05,
        "rows": [{"kv_dtype": "bf16", "peak_concurrent_slots": 3},
                 {"kv_dtype": "int8", "peak_concurrent_slots": 7}],
    }, tag="engine_decode_capacity")
    assert line["value"] == 0.0 and line["unit"] == "x"
    assert line["cpu_sanity"]["capacity_ok"] is True
    assert line["budgets"]["compile_time_s"]["value"] == 3.0
    assert line["budgets"]["step_time_s"]["budget"] == 120.0
    assert "error" not in line
    bench.persist_tpu_result({"metric": "engine_capacity", "value": 2.1,
                              "backend": "tpu"}, {},
                             tag="engine_decode_capacity")
    assert bench.load_last_tpu(tag="engine_decode_capacity")["value"] == 2.1
    assert bench.load_last_tpu() is None  # headline untouched


def test_capacity_bench_in_watch_jobs():
    """ISSUE 13: the fixed-pool-bytes capacity bench is in the tunnel-up
    capture list (own watchdog, bench evidence predicate)."""
    from tools.tpu_watch import JOBS

    by_name = {name: (cmd, bounded, pred) for name, cmd, bounded, pred in JOBS}
    assert "bench_decode_capacity" in by_name
    cmd, bounded, pred = by_name["bench_decode_capacity"]
    assert "--mode" in cmd and "capacity" in cmd
    assert bounded is False and pred is _bench_on_tpu


def test_committed_capacity_evidence_is_valid():
    """The committed CPU-sanity evidence (BENCH_decode_capacity_cpu_
    sanity.json) satisfies the acceptance bar: headline 0 off-TPU, the
    int8 arm sustains >= 2x the bf16 arm's peak concurrent slots at the
    SAME pool byte budget, the prefix hit rate is no worse, greedy
    tokens matched on the sanity horizon, and budgets populated without
    violations."""
    from pathlib import Path

    path = (Path(__file__).parent.parent
            / "BENCH_decode_capacity_cpu_sanity.json")
    rec = json.loads(path.read_text())
    assert rec["value"] == 0.0 and rec["backend"] == "cpu"
    sanity = rec["cpu_sanity"]
    assert sanity["capacity_ok"] is True
    assert sanity["greedy_match"] is True
    assert sanity["slot_ratio"] >= 2.0
    by = {r["kv_dtype"]: r for r in sanity["rows"]
          if "peak_concurrent_slots" in r}
    assert set(by) == {"bf16", "int8"}
    # SAME byte budget on both arms — the whole point of the bench
    assert (by["int8"]["pool_budget_bytes"]
            == by["bf16"]["pool_budget_bytes"])
    assert (by["int8"]["peak_concurrent_slots"]
            >= 2 * by["bf16"]["peak_concurrent_slots"])
    # int8 value bytes actually fit the budget, scale overhead included
    assert (by["int8"]["kv_pool_bytes"] + by["int8"]["kv_scale_bytes"]
            <= by["int8"]["pool_budget_bytes"])
    assert sanity["hit_rate_int8"] >= sanity["hit_rate_bf16"]
    assert "compile_time_s" in rec["budgets"]
    assert "error" not in rec
    stamped = dict(rec)
    stamped["error"] = "watchdog: engine decode bench exceeded 1500s"
    assert not _bench_on_tpu(json.dumps(stamped))


def test_trace_cost_budget_on_observability_line(evidence_dir):
    """ROADMAP item 4 leftover: the observability evidence line carries
    tracer-cost budget verdicts — within limits it annotates, a tracer
    regression stamps ``error`` the watch predicate rejects."""
    ok = bench.cpu_contract_line({
        "metric": "train_observability_overhead_llama470m_1chip",
        "value": 1.9, "unit": "steps/s", "backend": "cpu",
        "overhead_pct": 1.2, "instrument_cost_us_per_step": 110.0,
    }, tag="observability")
    assert ok["budgets"]["instrument_cost_us_per_step"]["budget"] == 2000.0
    assert ok["budgets"]["overhead_pct"]["budget"] == 10.0
    assert "error" not in ok

    drifted = bench.cpu_contract_line({
        "metric": "train_observability_overhead_llama470m_1chip",
        "value": 1.9, "unit": "steps/s", "backend": "cpu",
        "overhead_pct": 1.2, "instrument_cost_us_per_step": 5000.0,
    }, tag="observability")
    assert "instrument_cost_us_per_step" in drifted["error"]
    assert not _bench_on_tpu(json.dumps(drifted))


def test_resilience_smoke_in_watch_jobs():
    """ISSUE 3: the resilience chaos smoke is in the tunnel-up capture
    list.  Unlike the bench jobs it IS bounded by --job_timeout: its
    orchestrator has no internal watchdog, and its chaos children run on
    CPU (mid-step TPU kills wedge the tunnel), so a last-resort kill of
    the orchestrator cannot wedge anything."""
    from tools.tpu_watch import JOBS

    by_name = {name: (cmd, bounded, pred) for name, cmd, bounded, pred in JOBS}
    assert "resilience_chaos" in by_name
    cmd, bounded, pred = by_name["resilience_chaos"]
    assert cmd[-1].endswith("resilience_smoke.py")
    assert bounded is True and pred is _bench_on_tpu


def test_resilience_smoke_cpu_contract(evidence_dir):
    """Off-TPU the smoke reports headline 0 under the bench contract, with
    the chaos measurements riding in cpu_sanity; TPU evidence goes to its
    own tagged file and never clobbers the headline record."""
    line = bench.cpu_contract_line({
        "metric": "resilience_chaos_goodput_1chip",
        "value": 87.5, "unit": "%goodput", "backend": "cpu",
        "passed": True,
        "chaos": {"bitwise_identical": True, "attempt_classes":
                  ["signal", "clean"]},
    }, tag="resilience")
    assert line["value"] == 0.0 and line["unit"] == "%goodput"
    assert line["cpu_sanity"]["chaos"]["bitwise_identical"] is True
    assert not _bench_on_tpu(json.dumps(line))
    bench.persist_tpu_result({"metric": "resilience_chaos_goodput_1chip",
                              "value": 91.0, "backend": "tpu"}, {},
                             tag="resilience")
    assert bench.load_last_tpu(tag="resilience")["value"] == 91.0
    assert bench.load_last_tpu() is None  # headline untouched


def test_observability_bench_in_watch_jobs():
    """ISSUE 4: the observability overhead bench is in the tunnel-up
    capture list with the bench-style contract (own watchdog — no
    subprocess timeout — and the bench evidence predicate)."""
    from tools.tpu_watch import JOBS

    by_name = {name: (cmd, bounded, pred) for name, cmd, bounded, pred in JOBS}
    assert "bench_observability" in by_name
    cmd, bounded, pred = by_name["bench_observability"]
    assert cmd[-1].endswith("bench_observability.py")
    assert bounded is False and pred is _bench_on_tpu


def test_observability_bench_cpu_contract(evidence_dir):
    """Off-TPU the observability bench reports headline 0 under the bench
    contract with the off/on comparison riding in cpu_sanity; TPU
    evidence goes to its own tagged file and never clobbers the
    headline."""
    line = bench.cpu_contract_line({
        "metric": "train_loop_observed_steps_s_1chip",
        "value": 6.7, "unit": "steps/s", "backend": "cpu",
        "baseline_steps_per_sec": 6.9, "overhead_pct": 1.9,
        "pair_ratios": [0.98, 0.99, 1.0, 1.01], "rounds": 4,
        "passed": True, "loss_bitwise_identical": True,
        "instrument_cost_us_per_step": 99.7,
    }, tag="observability")
    assert line["value"] == 0.0 and line["unit"] == "steps/s"
    assert line["cpu_sanity"]["overhead_pct"] == 1.9
    assert line["cpu_sanity"]["loss_bitwise_identical"] is True
    assert not _bench_on_tpu(json.dumps(line))
    bench.persist_tpu_result({"metric": "train_loop_observed_steps_s_1chip",
                              "value": 8.5, "backend": "tpu"}, {},
                             tag="observability")
    assert bench.load_last_tpu(tag="observability")["value"] == 8.5
    assert bench.load_last_tpu() is None  # headline untouched


def test_e2e_470m_contract_line():
    """tools/e2e_470m.py off-TPU: headline 0, and the watcher predicate
    must NOT count that line as captured evidence."""
    from tools.e2e_470m import cpu_contract_record

    line = cpu_contract_record()  # the record main() prints off-TPU
    assert line["value"] == 0 and line["vs_baseline"] == 0
    assert not _bench_on_tpu(json.dumps(line))
    tpu = dict(line, value=23.4, backend="tpu")
    assert _bench_on_tpu(json.dumps(tpu))


def test_e2e_470m_in_watch_jobs():
    from tools.tpu_watch import JOBS

    names = [n for n, _, _, _ in JOBS]
    assert "e2e_470m" in names
    # VERDICT round-4 item 1: the ≤60s un-killable micro-capture runs
    # FIRST, so a one-shot tunnel window lands evidence before the
    # 10-minute bench can be killed mid-step; stock bench is second.
    assert names[0] == "micro_capture"
    assert names[1] == "bench_stock"
    # item 8: the TPU e2e is the full-epoch staged recipe
    e2e_cmd = dict((n, c) for n, c, _, _ in JOBS)["e2e_470m"]
    assert "--stage_iters" in e2e_cmd


def test_micro_capture_phase_persistence(evidence_dir, monkeypatch):
    """Each phase upgrade atomically rewrites the micro evidence file, and
    fills the headline slot only while it is empty (a real stock bench
    record must never be clobbered by a micro one)."""
    from tools import tpu_micro_capture as mc

    monkeypatch.setattr(mc, "MICRO_PATH",
                        str(evidence_dir / "BENCH_LAST_TPU_micro.json"))
    monkeypatch.setattr(mc, "LAST_TPU_PATH",
                        str(evidence_dir / "BENCH_LAST_TPU.json"))
    mc._persist({"metric": mc.METRIC, "phase": "contact", "value": 0.0,
                 "backend": "tpu", "micro": True})
    with open(mc.MICRO_PATH) as f:
        assert json.load(f)["phase"] == "contact"
    with open(mc.LAST_TPU_PATH) as f:
        assert json.load(f)["phase"] == "contact"  # filled-if-absent
    # later phases must UPGRADE a headline that still holds a micro record
    # (otherwise "contact" value-0 would block its own "timed" upgrade)
    mc._persist({"metric": mc.METRIC, "phase": "timed", "value": 99.0,
                 "backend": "tpu", "micro": True})
    with open(mc.LAST_TPU_PATH) as f:
        assert json.load(f)["phase"] == "timed"
    # headline now "taken" by a stock record: micro upgrades must not touch it
    with open(mc.LAST_TPU_PATH, "w") as f:
        json.dump({"metric": bench.METRIC, "value": 40.0}, f)
    mc._persist({"metric": mc.METRIC, "phase": "timed", "value": 123.4,
                 "backend": "tpu"})
    with open(mc.MICRO_PATH) as f:
        assert json.load(f)["phase"] == "timed"
    with open(mc.LAST_TPU_PATH) as f:
        assert json.load(f)["value"] == 40.0


def test_micro_capture_first_and_unbounded():
    """The micro capture self-exits via phases + watchdog; tpu_watch must
    not impose a subprocess timeout (killing a tunnel client mid-step
    wedges the tunnel), and its evidence predicate is the bench one."""
    from tools.tpu_watch import JOBS

    name, cmd, bounded, pred = JOBS[0]
    assert name == "micro_capture"
    assert cmd[-1].endswith("tpu_micro_capture.py")
    assert bounded is False and pred is _bench_on_tpu


def test_watch_evidence_autocommit(tmp_path, monkeypatch):
    """A captured job's evidence files are git-committed immediately — a
    one-shot tunnel window must not depend on the builder noticing before
    the round (or the session) ends."""
    import subprocess

    from tools import tpu_watch as tw

    repo = tmp_path / "r"
    repo.mkdir()
    subprocess.run(["git", "init", "-q"], cwd=repo, check=True)
    subprocess.run(["git", "-C", str(repo), "config", "user.email", "t@t"],
                   check=True)
    subprocess.run(["git", "-C", str(repo), "config", "user.name", "t"],
                   check=True)
    (repo / "BENCH_LAST_TPU_micro.json").write_text('{"backend": "tpu"}\n')
    monkeypatch.setattr(tw, "REPO", str(repo))
    tw._commit_evidence("micro_capture")
    log = subprocess.run(["git", "-C", str(repo), "log", "--oneline"],
                         capture_output=True, text=True).stdout
    assert "micro_capture evidence captured" in log
    # idempotent: nothing staged -> no second commit, no error
    tw._commit_evidence("micro_capture")
    log2 = subprocess.run(["git", "-C", str(repo), "log", "--oneline"],
                          capture_output=True, text=True).stdout
    assert log2.count("evidence captured") == 1


def test_pause_protocol_resolves_descendants():
    """MLT_PAUSE_PIDS entries expand to the live process tree at signal
    time (the e2e trainer respawns its compute child every resume stage)."""
    import subprocess

    from tools.tpu_watch import _descendants

    child = subprocess.Popen([sys.executable, "-c",
                              "import time; time.sleep(30)"])
    try:
        tree = _descendants(os.getpid())
        assert os.getpid() in tree and child.pid in tree
    finally:
        child.kill()
        child.wait()


def test_e2e_staged_helpers(tmp_path):
    """parse_train_loss survives format drift (ADVICE r4 #3); done_iters
    reads the tracker and is robust to absence/garbage."""
    from tools.e2e_470m import done_iters, parse_train_loss

    out = ("iteration   50/ 100 | lm loss: 7.234052 | lr: 1e-4 |\n"
           "noise\n"
           "iteration  100/ 100 | lm loss: 5.299069 | lr: 9e-5 |\n")
    assert parse_train_loss(out) == 5.299069
    assert parse_train_loss("iteration 1 | lm loss: garbage | x") is None
    assert parse_train_loss("") is None

    assert done_iters(str(tmp_path)) == 0  # no tracker
    (tmp_path / "latest_checkpointed_iteration.txt").write_text("250\n")
    assert done_iters(str(tmp_path)) == 250
    (tmp_path / "latest_checkpointed_iteration.txt").write_text("release")
    assert done_iters(str(tmp_path)) == 0
    (tmp_path / "latest_checkpointed_iteration.txt").write_text("junk")
    assert done_iters(str(tmp_path)) == 0


# ---------------------------------------------------------------------------
# ISSUE 6: host-cost budgets + the tp mesh bench
# ---------------------------------------------------------------------------


def test_budgets_annotate_within_limits(evidence_dir):
    """A contract line whose compile/step/dispatch costs sit inside the
    budgets gains the budgets block and NO error."""
    line = bench.cpu_contract_line({
        "metric": "m", "value": 1.0, "unit": "x", "backend": "cpu",
        "compile_time_s": 40.0, "step_time_s": 20.0,
        "step_time_dispatch_s": 0.1,
    })
    assert "error" not in line
    assert line["budgets"]["compile_time_s"]["value"] == 40.0
    assert line["budgets"]["compile_time_s"]["budget"] == 180.0
    assert line["budgets"]["step_time_s"]["budget"] == 120.0


def test_budgets_fail_loudly_on_drift(evidence_dir):
    """The BENCH_r02-r05 drift shape (compile 38s -> 100s -> beyond) must
    flip the line to an error the watch predicate rejects — no more silent
    upward creep across evidence files."""
    line = bench.cpu_contract_line({
        "metric": "m", "value": 1.0, "unit": "x", "backend": "cpu",
        "compile_time_s": 500.0, "step_time_s": 20.0,
    })
    assert "budget exceeded" in line["error"]
    assert any("compile_time_s" in v for v in line["budget_exceeded"])
    # an error line is not TPU evidence
    assert not _bench_on_tpu(json.dumps(line))


def test_budgets_env_override(evidence_dir, monkeypatch):
    monkeypatch.setenv("MLT_BENCH_BUDGET_STEP_TIME_S", "1.0")
    line = bench.apply_budgets({"cpu_sanity": {"step_time_s": 2.0},
                                "metric": "m"})
    assert "error" in line and "step_time_s" in line["error"]


def test_budgets_skip_missing_fields(evidence_dir):
    """Benches that don't report a field aren't judged on it."""
    line = bench.apply_budgets({"cpu_sanity": {"hit_rate": 0.9},
                                "metric": "m"})
    assert "error" not in line and "budgets" not in line


def test_tp_bench_cpu_contract(evidence_dir):
    """bench_tp.py rides the same off-TPU contract: headline 0, per-layout
    mechanism checks under cpu_sanity, budget fields populated from the
    largest layout, tagged TPU evidence file."""
    line = bench.cpu_contract_line({
        "metric": "tp_mesh_train_steps_s", "value": 25.9, "unit": "steps/s",
        "backend": "cpu",
        "layouts": [
            {"tp": 1, "all_reduce_count": 0, "loss": 6.1},
            {"tp": 4, "all_reduce_count": 67, "loss": 6.1},
        ],
        "loss_parity_vs_tp1": {"tp4_loss_delta": 0.0},
        "engine_tokens_match_tp1": True,
        "step_time_s": 0.04, "step_time_dispatch_s": 0.04,
        "compile_time_s": 2.0,
    }, tag="tp")
    assert line["value"] == 0.0
    assert line["cpu_sanity"]["layouts"][1]["all_reduce_count"] > 0
    assert line["budgets"]["compile_time_s"]["value"] == 2.0
    assert "error" not in line
    bench.persist_tpu_result({"metric": "tp_mesh_train_steps_s",
                              "value": 12.0, "backend": "tpu"}, {}, tag="tp")
    assert bench.load_last_tpu(tag="tp")["value"] == 12.0
    assert bench.load_last_tpu() is None


def test_tp_bench_in_watch_jobs():
    """ISSUE 6: the tp mesh bench is in the tunnel-up capture list."""
    from tools.tpu_watch import JOBS

    by_name = {name: (cmd, bounded, pred) for name, cmd, bounded, pred in JOBS}
    assert "bench_tp" in by_name
    cmd, bounded, pred = by_name["bench_tp"]
    assert "bench_tp.py" in cmd[1]
    assert bounded is False and pred is _bench_on_tpu


def test_tp_bench_committed_cpu_evidence():
    """The CPU-sanity evidence JSON is committed with the budget fields
    populated (ISSUE 6 acceptance)."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_tp_cpu_sanity.json")
    with open(path) as f:
        line = json.load(f)
    assert line["metric"] == "tp_mesh_train_steps_s"
    assert line["value"] == 0.0  # CPU headline contract
    assert "error" not in line
    for field in ("compile_time_s", "step_time_s", "step_time_dispatch_s"):
        assert field in line["budgets"], field
    sanity = line["cpu_sanity"]
    by_tp = {r["tp"]: r for r in sanity["layouts"] if "skipped" not in r}
    assert by_tp[4]["all_reduce_count"] > 0
    assert by_tp[1]["all_reduce_count"] == 0
    assert sanity["loss_parity_vs_tp1"]["tp4_loss_delta"] <= 1e-4
    assert sanity["engine_tokens_match_tp1"] is True


def test_tp_bench_committed_overlap_evidence():
    """ISSUE 15 acceptance: the committed bench_tp evidence carries the
    overlap arm with the mechanism MACHINE-asserted — ppermute chain +
    forward-tp{N}-overlap scope in the compiled HLO, loss parity within
    rel 1e-4 of the overlap-off row (chunked-GEMM reassociation:
    tolerance, not bitwise), engine greedy tokens identical."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_tp_cpu_sanity.json")
    with open(path) as f:
        line = json.load(f)
    arm = line["cpu_sanity"]["overlap"]
    assert arm["mechanism_ok"] is True
    rows = [r for r in arm["layouts"] if "skipped" not in r]
    assert rows, "overlap arm has no measured layouts"
    for r in rows:
        assert r["tp_overlap"] == "ring"
        assert r["overlap_scope_in_hlo"] is True
        assert r["ppermute_chain"] is True
        assert r["loss_rel_vs_off"] <= 1e-4
        assert r["engine_tokens_match_off"] is True
        # the ring re-associates but must not lose the tp collectives'
        # semantics: the layout still reports tp-sharded params
        assert r["tp_sharded_leaves"] > 0


def test_tp_bench_overlap_arm_shape():
    """run_overlap_arm contract on synthetic rows: mechanism_ok goes
    false when any check fails, and tp=1 rows are never ring-armed."""
    import bench_tp

    base = [{"tp": 1, "step_time_s": 1.0, "loss": 6.0,
             "collective_permute_count": 0}]
    arm = bench_tp.run_overlap_arm([1], 1, 64, 2, 64, 0, base, [])
    assert arm["layouts"] == [] and arm["mechanism_ok"] is True


# ---------------------------------------------------------------------------
# ISSUE 12: bench-trajectory drift detector (tools/bench_drift.py)
# ---------------------------------------------------------------------------


def test_bench_drift_in_watch_jobs():
    """The drift check rides the tunnel-up capture list right after the
    static analysis: bounded (it only reads committed JSON) and captured
    whenever a parseable verdict line lands (drift is a finding to
    bisect, not a retryable failure)."""
    from tools.tpu_watch import JOBS, _drift_ran

    by_name = {name: (cmd, bounded, pred) for name, cmd, bounded, pred in JOBS}
    assert "bench_drift" in by_name
    cmd, bounded, pred = by_name["bench_drift"]
    assert cmd[-1].endswith("bench_drift.py")
    assert bounded is True and pred is _drift_ran
    assert pred(json.dumps({"bench_drift": 1, "verdict": "ok"}))
    assert pred(json.dumps({"bench_drift": 1, "verdict": "drift"}))
    assert not pred("Traceback (most recent call last):")
    assert not pred(json.dumps({"metric": "x", "value": 0.0}))


def test_bench_drift_computation_synthetic():
    """Per-metric drift math: ratio of newest to earliest committed
    round, direction-aware thresholds, rounds without the metric
    skipped."""
    from tools.bench_drift import compute_drift

    rows = [
        (2, "BENCH_r02.json", {"step_time_s": 10.0, "compile_time_s": 40.0,
                               "tokens_per_sec": 100.0}),
        (3, "BENCH_r03.json", {"step_time_s": 11.0}),
        (5, "BENCH_r05.json", {"step_time_s": 12.0, "compile_time_s": 44.0,
                               "tokens_per_sec": 90.0}),
    ]
    res = compute_drift(rows)
    assert res["verdict"] == "ok"
    m = res["metrics"]["step_time_s"]
    assert m["rounds"] == 3 and m["ratio"] == 1.2 and not m["exceeded"]
    assert res["metrics"]["compile_time_s"]["rounds"] == 2
    # now push step time past the ceiling
    rows.append((6, "BENCH_r06.json", {"step_time_s": 31.0}))
    res = compute_drift(rows)
    assert res["verdict"] == "drift"
    assert res["metrics"]["step_time_s"]["exceeded"] is True
    assert res["metrics"]["tokens_per_sec"]["exceeded"] is False
    # thresholds are configurable
    res = compute_drift(rows, {"step_time_s": 4.0})
    assert res["metrics"]["step_time_s"]["exceeded"] is False


def test_bench_drift_flags_committed_trajectory():
    """ROADMAP item 3 CLOSED (ISSUE 15): the r02->r05 "drift" was
    root-caused as host contention, not code — the round-5 record
    (step 52.2s / compile 100.4s) was measured while the staged 470M
    e2e jobs shared the single-core host (both metrics inflated by the
    same ~2.1x, the signature of CPU-time division), and re-measuring
    the EXACT r05 tree on an idle host gives 24.4s/47.6s, matching the
    r04 tree (23.6s/47.8s) and HEAD.  BENCH_r06.json is the clean
    re-measurement (its ``note`` carries the bisect evidence).  This
    test now pins the FIX: the refreshed trajectory must stay within
    the drift thresholds — any future round that trips them is a real
    regression to bisect, not carried debt."""
    from tools.bench_drift import compute_drift, load_trajectory

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rows = load_trajectory(repo)
    assert len(rows) >= 5, "committed BENCH_r* trajectory went missing"
    assert rows[-1][1] == "BENCH_r06.json", (
        "the root-cause refresh round went missing — newest round is "
        f"{rows[-1][1]}")
    res = compute_drift(rows)
    assert res["verdict"] == "ok", res
    for field in ("step_time_s", "compile_time_s", "tokens_per_sec"):
        assert res["metrics"][field]["exceeded"] is False, res["metrics"]
    # the contaminated r05 point stays committed (history is honest);
    # only the newest-vs-earliest ratio gates
    assert res["metrics"]["step_time_s"]["ratio"] < 1.5
    assert res["metrics"]["compile_time_s"]["ratio"] < 1.5


# ---------------------------------------------------------------------------
# ISSUE 14: two-pass graftcheck sweep wall-time + changed-only warm cost
# ---------------------------------------------------------------------------


def test_graftcheck_two_pass_sweep_walltime(tmp_path):
    """The whole-repo two-pass sweep (per-file rules + lock-order +
    wire-contract analyzers) stays under 45 s wall — the budget that
    keeps it viable as a tier-1 gate and a tpu_watch job.  The warm
    --changed-only path (pass-1 scoped to changed files, pass-2 facts
    from the cache) must be a small fraction of that: it is the local
    pre-commit loop."""
    from tools.graftcheck import core

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    targets = [os.path.join(repo, t)
               for t in ("megatron_llm_tpu", "tools", "tasks", "tests")]
    cache = str(tmp_path / "factcache.json")
    full = core.run(targets, root=repo, fact_cache_path=cache)
    assert full.files > 150
    assert full.seconds < 45, f"full sweep {full.seconds:.1f}s > 45s"
    warm = core.run(targets, root=repo, changed_files=[],
                    fact_cache_path=cache)
    assert warm.changed_only
    assert warm.seconds < max(5.0, full.seconds / 2), (
        f"warm changed-only run {warm.seconds:.1f}s — the fact cache "
        f"is not being hit")
    # the cached pass-2 still sees the whole project
    lo = warm.artifacts["lockorder"]
    assert ("ContinuousBatchingEngine._lock", "FlightRecorder._lock") \
        in {(e["from"], e["to"]) for e in lo["edges"]}


def test_graftcheck_lockorder_evidence_committed():
    """tools/graftcheck/lockorder.json rides the same reviewed-evidence
    contract as the BENCH files: present, schema-valid, cycle-free,
    with the engine→recorder edge the flight recorder's safety argument
    rests on.  (Equality with the freshly derived graph is pinned in
    tests/test_graftcheck.py::test_lockorder_committed_evidence.)"""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "tools", "graftcheck", "lockorder.json")
    assert os.path.exists(path), "committed lock-graph evidence missing"
    with open(path) as f:
        doc = json.load(f)
    assert doc["graftcheck_lockorder"] == 1
    assert doc["cycles"] == []
    assert doc["order"], "committed graph must be acyclic + ordered"
    assert len(doc["nodes"]) >= 15
    assert ("ContinuousBatchingEngine._lock", "FlightRecorder._lock") \
        in {(e["from"], e["to"]) for e in doc["edges"]}
    for e in doc["edges"]:
        assert e["examples"], "every edge needs a source example site"


def test_graftcheck_watch_job_two_pass():
    """The tpu_watch graftcheck job runs the full two-pass target set
    and refreshes the committed lock-graph evidence; its predicate
    still reads the one-line JSON (crash = retry, findings =
    captured)."""
    from tools.tpu_watch import JOBS, _graftcheck_ran

    by_name = {name: (cmd, bounded, pred)
               for name, cmd, bounded, pred in JOBS}
    cmd, bounded, pred = by_name["graftcheck"]
    assert bounded
    joined = " ".join(cmd)
    assert "--lockorder-out" in joined
    assert "tools/graftcheck/lockorder.json" in joined
    for target in ("megatron_llm_tpu", "tools", "tasks", "tests"):
        assert target in cmd
    assert pred is _graftcheck_ran


# ---------------------------------------------------------------------------
# ISSUE 17: pipelined multi-tick dispatch bench
# ---------------------------------------------------------------------------


def test_pipeline_bench_cpu_contract(evidence_dir):
    """bench_decode.py --mode pipeline (ISSUE 17) reuses the off-TPU
    contract: headline 0, the depth-sweep speedup/host-gap comparison
    rides under cpu_sanity with budget fields populated, TPU evidence
    goes to its own tagged file."""
    line = bench.cpu_contract_line({
        "metric": "engine_pipeline_decode_speedup_llama470m_c8_1chip",
        "value": 1.6, "unit": "x", "backend": "cpu",
        "speedup_ok": True, "lossless": True, "best_depth": 8,
        "depths_swept": [0, 1, 2, 8], "host_gap_reduction": 3.0,
        "compile_time_s": 2.0, "step_time_s": 0.001,
        "rows": [{"concurrency": 8, "speedup_best": 1.6,
                  "best_depth": 8, "host_gap_reduction": 3.0,
                  "lossless": True}],
    }, tag="engine_decode_pipeline")
    assert line["value"] == 0.0 and line["unit"] == "x"
    assert line["cpu_sanity"]["speedup_ok"] is True
    assert line["cpu_sanity"]["lossless"] is True
    assert line["budgets"]["compile_time_s"]["value"] == 2.0
    assert "error" not in line
    bench.persist_tpu_result({"metric": "engine_pipeline", "value": 1.7,
                              "backend": "tpu"}, {},
                             tag="engine_decode_pipeline")
    assert bench.load_last_tpu(tag="engine_decode_pipeline")["value"] == 1.7
    assert bench.load_last_tpu() is None  # headline untouched


def test_pipeline_bench_in_watch_jobs():
    """ISSUE 17: the pipelined-dispatch bench is in the tunnel-up
    capture list (own watchdog, bench evidence predicate)."""
    from tools.tpu_watch import JOBS

    by_name = {name: (cmd, bounded, pred) for name, cmd, bounded, pred in JOBS}
    assert "bench_decode_pipeline" in by_name
    cmd, bounded, pred = by_name["bench_decode_pipeline"]
    assert "--mode" in cmd and "pipeline" in cmd
    assert bounded is False and pred is _bench_on_tpu


def test_committed_pipeline_evidence_is_valid():
    """The committed CPU-sanity evidence (BENCH_decode_pipeline_cpu_
    sanity.json) satisfies the acceptance bar: headline 0 off-TPU, the
    best pipelined arm at the highest concurrency is >= 1.5x depth-0
    decode tok/s with a measurably reduced host gap, every arm emitted
    byte-identical tokens to depth 0, and budgets populated without
    violations."""
    from pathlib import Path

    path = (Path(__file__).parent.parent
            / "BENCH_decode_pipeline_cpu_sanity.json")
    rec = json.loads(path.read_text())
    assert rec["value"] == 0.0 and rec["backend"] == "cpu"
    sanity = rec["cpu_sanity"]
    assert sanity["speedup_ok"] is True
    assert sanity["lossless"] is True
    assert 0 in sanity["depths_swept"]
    assert any(d > 0 for d in sanity["depths_swept"])
    # headline row = highest concurrency swept
    top = max(sanity["rows"], key=lambda r: r["concurrency"])
    assert top["speedup_best"] >= 1.5
    assert top["host_gap_reduction"] > 1.0
    assert top["lossless"] is True
    by_depth = {d["depth"]: d for d in top["depths"]}
    assert 0 in by_depth and top["best_depth"] in by_depth
    best = by_depth[top["best_depth"]]
    # fewer host dispatches and less accumulated host gap than depth 0
    assert best["dispatches"] < by_depth[0]["dispatches"]
    assert best["host_gap_total_s"] < by_depth[0]["host_gap_total_s"]
    assert "compile_time_s" in rec["budgets"]
    assert "error" not in rec
    # an error-stamped line of this shape must be rejected by the watch
    # evidence predicate, not captured
    stamped = dict(rec)
    stamped["error"] = "watchdog: engine decode bench exceeded 1500s"
    assert not _bench_on_tpu(json.dumps(stamped))


def test_streaming_bench_cpu_contract(evidence_dir):
    """bench_decode.py --mode streaming (ISSUE 18) reuses the off-TPU
    contract: headline 0, the streamed-vs-buffered TTFT comparison and
    the admission-queue burst rows ride under cpu_sanity with budget
    fields populated, TPU evidence goes to its own tagged file."""
    line = bench.cpu_contract_line({
        "metric":
            "serving_stream_first_token_speedup_llama470m_c8_2rep_1chip",
        "value": 2.4, "unit": "x", "backend": "cpu",
        "first_token_speedup": 2.4, "stream_ok": True,
        "stamp_ratio": 1.1, "stamp_ok": True,
        "buffered_first_byte_is_total": True, "identity_ok": True,
        "baseline_dropped": 8, "admission_dropped": 0,
        "compile_time_s": 3.0, "step_time_s": 0.01,
        "rows": [{"arm": "streamed", "client_ttft_mean_ms": 55.0,
                  "replica_stamp_mean_ms": 50.0, "total_mean_ms": 170.0},
                 {"arm": "buffered", "client_ttft_mean_ms": 132.0,
                  "total_mean_ms": 135.0},
                 {"admission_queue": False, "requests": 12, "ok": 4,
                  "dropped": 8},
                 {"admission_queue": True, "requests": 12, "ok": 12,
                  "dropped": 0}],
    }, tag="engine_decode_streaming")
    assert line["value"] == 0.0 and line["unit"] == "x"
    assert line["cpu_sanity"]["stream_ok"] is True
    assert line["cpu_sanity"]["admission_dropped"] == 0
    assert line["budgets"]["compile_time_s"]["value"] == 3.0
    assert "error" not in line
    bench.persist_tpu_result({"metric": "serving_stream", "value": 2.6,
                              "backend": "tpu"}, {},
                             tag="engine_decode_streaming")
    assert bench.load_last_tpu(tag="engine_decode_streaming")["value"] == 2.6
    assert bench.load_last_tpu() is None  # headline untouched


def test_streaming_bench_in_watch_jobs():
    """ISSUE 18: the streaming serving-tier bench is in the tunnel-up
    capture list (own watchdog, bench evidence predicate)."""
    from tools.tpu_watch import JOBS

    by_name = {name: (cmd, bounded, pred) for name, cmd, bounded, pred in JOBS}
    assert "bench_decode_streaming" in by_name
    cmd, bounded, pred = by_name["bench_decode_streaming"]
    assert "--mode" in cmd and "streaming" in cmd
    assert bounded is False and pred is _bench_on_tpu


def test_committed_streaming_evidence_is_valid():
    """The committed CPU-sanity evidence (BENCH_decode_streaming_cpu_
    sanity.json) satisfies the acceptance bar: headline 0 off-TPU, the
    streamed client's first byte lands within the stamp-honesty gate and
    strictly before the buffered client's (speedup >= 1), the streamed
    terminal body matched the buffered response byte-for-byte, the
    saturation burst 503'd without the admission queue and dropped
    nothing with it, budgets populated without violations."""
    from pathlib import Path

    path = (Path(__file__).parent.parent
            / "BENCH_decode_streaming_cpu_sanity.json")
    rec = json.loads(path.read_text())
    assert rec["value"] == 0.0 and rec["backend"] == "cpu"
    sanity = rec["cpu_sanity"]
    assert sanity["stream_ok"] is True
    assert sanity["stamp_ok"] is True
    assert sanity["identity_ok"] is True
    assert sanity["buffered_first_byte_is_total"] is True
    assert sanity["first_token_speedup"] >= 1.0
    by_arm = {r["arm"]: r for r in sanity["rows"] if "arm" in r}
    assert set(by_arm) == {"streamed", "buffered"}
    # streaming delivers the first token earlier than the buffered
    # response delivers anything at all
    assert (by_arm["streamed"]["client_ttft_mean_ms"]
            < by_arm["buffered"]["client_ttft_mean_ms"])
    # every streamed response carried the replica's X-MLT-TTFT-S stamp
    assert by_arm["streamed"]["stamped"] == sanity["workload"]["concurrency"]
    bursts = {r["admission_queue"]: r for r in sanity["rows"]
              if "admission_queue" in r}
    assert set(bursts) == {False, True}
    assert bursts[False]["dropped"] > 0  # the burst genuinely saturates
    assert bursts[True]["dropped"] == 0
    assert bursts[True]["ok"] == bursts[True]["requests"]
    assert bursts[True]["admission_stats"]["overflows"] == 0
    assert "compile_time_s" in rec["budgets"]
    assert "error" not in rec
    # an error-stamped line of this shape must be rejected by the watch
    # evidence predicate, not captured
    stamped = dict(rec)
    stamped["error"] = "watchdog: engine decode bench exceeded 1500s"
    assert not _bench_on_tpu(json.dumps(stamped))


def test_disagg_bench_cpu_contract(evidence_dir):
    """bench_decode.py --mode disagg (ISSUE 19) reuses the off-TPU
    contract: headline 0, the unified-vs-split fleet TPOT comparison and
    the per-arm/class rows ride under cpu_sanity with budget fields
    populated, TPU evidence goes to its own tagged file."""
    line = bench.cpu_contract_line({
        "metric":
            "serving_disagg_decode_p99_tpot_speedup_llama470m_2rep_1chip",
        "value": 1.4, "unit": "x", "backend": "cpu",
        "decode_tpot_p99_speedup": 1.4, "decode_tpot_mean_speedup": 1.3,
        "disagg_ok": True, "identity_ok": True,
        "handoffs": 7.0, "handoff_failures": 0.0,
        "long_ttft_mean_ms": {"unified": 2100.0, "split": 1800.0},
        "compile_time_s": 6.0, "step_time_s": 0.05,
        "rows": [{"arm": "unified+unified", "class": "short",
                  "requests": 24, "tpot_p99_ms": 104.0},
                 {"arm": "prefill+decode", "class": "short",
                  "requests": 24, "tpot_p99_ms": 76.0}],
    }, tag="engine_decode_disagg")
    assert line["value"] == 0.0 and line["unit"] == "x"
    assert line["cpu_sanity"]["disagg_ok"] is True
    assert line["cpu_sanity"]["handoff_failures"] == 0.0
    assert line["budgets"]["compile_time_s"]["value"] == 6.0
    assert "error" not in line
    bench.persist_tpu_result({"metric": "serving_disagg", "value": 1.6,
                              "backend": "tpu"}, {},
                             tag="engine_decode_disagg")
    assert bench.load_last_tpu(tag="engine_decode_disagg")["value"] == 1.6
    assert bench.load_last_tpu() is None  # headline untouched


def test_disagg_bench_in_watch_jobs():
    """ISSUE 19: the disaggregated prefill/decode bench is in the
    tunnel-up capture list (own watchdog, bench evidence predicate)."""
    from tools.tpu_watch import JOBS

    by_name = {name: (cmd, bounded, pred) for name, cmd, bounded, pred in JOBS}
    assert "bench_decode_disagg" in by_name
    cmd, bounded, pred = by_name["bench_decode_disagg"]
    assert "--mode" in cmd and "disagg" in cmd
    assert bounded is False and pred is _bench_on_tpu


def test_committed_disagg_evidence_is_valid():
    """The committed CPU-sanity evidence (BENCH_decode_disagg_cpu_
    sanity.json) satisfies the acceptance bar: headline 0 off-TPU, the
    split fleet's short-class decode p99 TPOT beats the unified fleet's
    (speedup > 1), both arms produced byte-identical tokens, every long
    request in the split arm actually took the handoff path with zero
    failures and the unified arm never handed off, budgets populated
    without violations."""
    from pathlib import Path

    path = (Path(__file__).parent.parent
            / "BENCH_decode_disagg_cpu_sanity.json")
    rec = json.loads(path.read_text())
    assert rec["value"] == 0.0 and rec["backend"] == "cpu"
    sanity = rec["cpu_sanity"]
    assert sanity["disagg_ok"] is True
    assert sanity["identity_ok"] is True
    assert sanity["decode_tpot_p99_speedup"] > 1.0
    assert sanity["handoff_failures"] == 0
    wl = sanity["workload"]
    # every long request (n_long clients x long_reqs each) hopped, plus
    # the warm-up request; the unified arm's router counter stays 0 (the
    # bench gates on it before reporting, so handoffs here are split-arm)
    assert sanity["handoffs"] >= wl["n_long"] * wl["long_reqs"]
    by_key = {(r["arm"], r["class"]): r for r in sanity["rows"]}
    assert set(by_key) == {("unified+unified", "short"),
                           ("unified+unified", "long"),
                           ("prefill+decode", "short"),
                           ("prefill+decode", "long")}
    # the headline: pure decode ticks beat prefill-polluted ones on the
    # saturated short class
    uni = by_key[("unified+unified", "short")]
    split = by_key[("prefill+decode", "short")]
    assert split["tpot_p99_ms"] < uni["tpot_p99_ms"]
    assert uni["requests"] == split["requests"] == (
        wl["n_short"] * wl["short_reqs"])
    assert "compile_time_s" in rec["budgets"]
    assert "error" not in rec
    # an error-stamped line of this shape must be rejected by the watch
    # evidence predicate, not captured
    stamped = dict(rec)
    stamped["error"] = "watchdog: engine decode bench exceeded 1500s"
    assert not _bench_on_tpu(json.dumps(stamped))


def test_pp_bench_cpu_contract(evidence_dir):
    """bench_decode.py --mode pp (ISSUE 20) reuses the off-TPU contract:
    headline 0, the pp-vs-equal-chip-tp decode ratio, the stage-bytes
    check and the HLO mechanism verdict ride under cpu_sanity with
    budget fields populated, TPU evidence goes to its own tagged file."""
    line = bench.cpu_contract_line({
        "metric": "engine_pp_decode_tok_s_ratio_llama470m_c8_eqchip",
        "value": 0.96, "unit": "x", "backend": "cpu",
        "pp_ok": True, "identity_ok": True, "stage_bytes_ok": True,
        "mechanism_ok": True, "stage_bytes_ratio": 0.25,
        "ratios_vs_equal_chip_pp1": {"pp2": 0.96, "pp4": 0.94},
        "compile_time_s": 19.0, "step_time_s": 0.01,
        "rows": [{"pp": 1, "tp": 1, "chips": 1, "decode_tok_s": 2300.0},
                 {"pp": 2, "tp": 1, "chips": 2, "decode_tok_s": 1170.0}],
    }, tag="engine_decode_pp")
    assert line["value"] == 0.0 and line["unit"] == "x"
    assert line["cpu_sanity"]["pp_ok"] is True
    assert line["cpu_sanity"]["mechanism_ok"] is True
    assert line["budgets"]["compile_time_s"]["value"] == 19.0
    assert "error" not in line
    bench.persist_tpu_result({"metric": "engine_pp", "value": 0.97,
                              "backend": "tpu"}, {},
                             tag="engine_decode_pp")
    assert bench.load_last_tpu(tag="engine_decode_pp")["value"] == 0.97
    assert bench.load_last_tpu() is None  # headline untouched


def test_pp_bench_in_watch_jobs():
    """ISSUE 20: the pipeline-parallel serving bench is in the tunnel-up
    capture list (own watchdog, bench evidence predicate)."""
    from tools.tpu_watch import JOBS

    by_name = {name: (cmd, bounded, pred) for name, cmd, bounded, pred in JOBS}
    assert "bench_decode_pp" in by_name
    cmd, bounded, pred = by_name["bench_decode_pp"]
    assert "--mode" in cmd and "pp" in cmd
    assert bounded is False and pred is _bench_on_tpu


def test_committed_pp_evidence_is_valid():
    """The committed CPU-sanity evidence (BENCH_decode_pp_cpu_sanity.
    json) satisfies the acceptance bar: headline 0 off-TPU, greedy
    tokens identical across every arm, per-stage KV bytes exactly
    kv_pool_bytes/pp (the servable-model-size multiplier), the
    stage-permute ppermute chain machine-asserted in the compiled tick
    HLO, and every pp arm's decode tok/s within 15% of the equal-chip
    pp=1 (tp-only) arm, budgets populated without violations."""
    from pathlib import Path

    path = (Path(__file__).parent.parent
            / "BENCH_decode_pp_cpu_sanity.json")
    rec = json.loads(path.read_text())
    assert rec["value"] == 0.0 and rec["backend"] == "cpu"
    sanity = rec["cpu_sanity"]
    assert sanity["pp_ok"] is True
    assert sanity["identity_ok"] is True
    assert sanity["stage_bytes_ok"] is True
    assert sanity["mechanism_ok"] is True
    # the acceptance bar: <= 15% decode tok/s cost at equal chips for
    # EVERY pipelined arm, with per-stage KV residency cut to 1/pp
    assert all(r >= 0.85
               for r in sanity["ratios_vs_equal_chip_pp1"].values())
    by_arm = {(r["pp"], r["tp"]): r for r in sanity["rows"]}
    wl = sanity["workload"]
    for pp in wl["pps"]:
        base, arm = by_arm[(1, pp)], by_arm[(pp, 1)]
        assert base["chips"] == arm["chips"] == pp  # equal-chip pairing
        assert arm["kv_stage_bytes"] == arm["kv_pool_bytes"] // pp
        assert base["kv_stage_bytes"] == base["kv_pool_bytes"]
        assert (arm["decode_tok_s"]
                >= 0.85 * base["decode_tok_s"])
    assert by_arm[(1, 1)]["chips"] == 1  # flat identity reference ran
    assert "compile_time_s" in rec["budgets"]
    assert "error" not in rec
    # an error-stamped line of this shape must be rejected by the watch
    # evidence predicate, not captured
    stamped = dict(rec)
    stamped["error"] = "watchdog: engine decode bench exceeded 1500s"
    assert not _bench_on_tpu(json.dumps(stamped))
