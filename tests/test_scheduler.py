"""Serving control plane tests (ISSUE 7, generation/scheduling/).

Gates: (1) the fcfs policy — the default — is the pre-policy engine,
token-for-token: same tokens AND log-probs as the PR 1 monolithic
reference, strict submission-order admission, nothing preempted or shed;
(2) preemption-by-page-release resumes BITWISE through the prefix cache
(tokens + log-probs, greedy and sampled, any cut point); (3) the
commitment ledger + page-state invariants hold through preempt/resume
churn (free + evictable always covers the admitted worst case); (4) the
priority policy's aging bound ends starvation; (5) the slo policy admits
earliest-deadline-first and sheds unmeetable deadlines; (6) admission
control is metrics-driven: EMA-drain Retry-After on 503s, per-priority
queue bounds, and the per-priority queue gauges update from one
scheduler-owned point.
"""

import time
from collections import Counter

import numpy as np
import pytest

import jax

from megatron_llm_tpu.generation import (
    ContinuousBatchingEngine,
    EngineOverloaded,
    RequestShed,
    get_policy,
)
from megatron_llm_tpu.generation.engine import NULL_PAGE
from megatron_llm_tpu.generation.scheduling import (
    FcfsPolicy,
    PriorityPolicy,
    SchedulerState,
    SloPolicy,
    available_policies,
)
from megatron_llm_tpu.generation.server import MegatronServer
from megatron_llm_tpu.models import init_model_params, make_config
from megatron_llm_tpu.observability import registry as obs_registry

VOCAB = 67
GKW = dict(top_k=1, termination_id=10 ** 9)


@pytest.fixture(scope="module")
def toy_model():
    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, ffn_hidden_size=128, seq_length=128,
        max_position_embeddings=256, vocab_size=VOCAB,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype="float32", use_flash_attn=False,
    )
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _engine(cfg, params, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq", 128)
    return ContinuousBatchingEngine(cfg, params, None, **kw)


def _prompt(n, off=0):
    return [2 + ((i + off) * 7) % 60 for i in range(n)]


def _drain(eng, reqs, timeout=60):
    eng.run_until_idle()
    return [r.result(timeout=timeout) for r in reqs]


# ---------------------------------------------------------------------------
# fcfs: the pre-policy engine, bitwise
# ---------------------------------------------------------------------------


def test_policy_registry():
    assert {"fcfs", "priority", "slo"} <= set(available_policies())
    assert get_policy("fcfs") is FcfsPolicy
    with pytest.raises(ValueError, match="unknown scheduling policy"):
        get_policy("lottery")


def test_fcfs_bitwise_parity_vs_monolithic_reference(toy_model):
    """Default engine (fcfs policy, chunked+cached) == the PR 1
    monolithic prefill engine on tokens AND log-probs — the policy
    extraction changed no bits.  Mirrors the pre-refactor parity contract
    (tests/test_prefix_cache.py), now through the policy layer."""
    cfg, params = toy_model
    jobs = [(_prompt(n, n), 10, dict(seed=n, **GKW)) for n in (3, 20, 40)]
    jobs.append((_prompt(24, 5), 10,
                 dict(temperature=0.8, top_p=0.9, seed=7,
                      termination_id=10 ** 9)))

    mono = _engine(cfg, params, prefill_chunk=0)
    ref = [mono.submit(p, g, **kw) for p, g, kw in jobs]
    res_ref = _drain(mono, ref)

    fcfs = _engine(cfg, params, sched_policy="fcfs")
    assert isinstance(fcfs.policy, FcfsPolicy)
    got = [fcfs.submit(p, g, **kw) for p, g, kw in jobs]
    res_got = _drain(fcfs, got)

    for (t1, lp1), (t2, lp2) in zip(res_ref, res_got):
        assert t1 == t2
        assert lp1 == lp2
    assert fcfs.preemptions == 0 and fcfs.shed_requests == 0


def test_fcfs_admission_is_submission_order(toy_model):
    """One slot, three queued requests: first tokens land in submit
    order — the fcfs head blocks, nothing skips it."""
    cfg, params = toy_model
    eng = _engine(cfg, params, max_slots=1)
    reqs = [eng.submit(_prompt(8, i), 4, seed=i, **GKW) for i in range(3)]
    _drain(eng, reqs)
    firsts = [r._t_first for r in reqs]
    assert firsts == sorted(firsts)


# ---------------------------------------------------------------------------
# Preemption by page release: bitwise resume
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cut,cache", [(1, True), (17, True), (33, True),
                                       (13, False)])
def test_preempt_resume_bitwise(toy_model, cut, cache):
    """Preempt a decoding request mid-stream, let it resume: tokens and
    log-probs are bitwise what an uninterrupted run produces.  With the
    cache on, resume re-matches the SAME physical pages out of the trie
    (near-zero recompute); with it off, the chunked re-prefill recomputes
    the tail — both land on identical bits (the PR 5 grid-aligned chunk
    invariant)."""
    cfg, params = toy_model
    prompt = _prompt(30)
    ref_eng = _engine(cfg, params)
    ref = ref_eng.submit(prompt, 40, seed=5, **GKW)
    (t_ref, lp_ref), = _drain(ref_eng, [ref])

    eng = _engine(cfg, params, prefix_cache=cache)
    hits0 = eng.prefix_hit_tokens
    req = eng.submit(prompt, 40, seed=5, **GKW)
    while len(req.generated) < cut:
        eng.step()
    assert eng.preempt(req)
    assert req._phase == "queued" and not req._pages
    (t, lp), = _drain(eng, [req])
    assert t == t_ref
    assert lp == lp_ref
    assert eng.preemptions == 1
    if cache:
        # resume matched the parked pages back out of the trie
        assert eng.prefix_hit_tokens - hits0 >= (cut // eng.page_size) \
            * eng.page_size


def test_preempt_resume_bitwise_sampled(toy_model):
    """The pinned PRNG key + resumed step counter continue the sampling
    stream exactly: a preempted temperature/top-p request matches its
    uninterrupted twin bitwise."""
    cfg, params = toy_model
    prompt = _prompt(30)
    kw = dict(temperature=0.8, top_p=0.9, seed=9, termination_id=10 ** 9)
    ref_eng = _engine(cfg, params)
    ref = ref_eng.submit(prompt, 30, **kw)
    (t_ref, lp_ref), = _drain(ref_eng, [ref])

    eng = _engine(cfg, params)
    req = eng.submit(prompt, 30, **kw)
    while len(req.generated) < 11:
        eng.step()
    assert eng.preempt(req)
    (t, lp), = _drain(eng, [req])
    assert t == t_ref and lp == lp_ref


def _assert_invariants(eng):
    """Page states exact + the commitment ledger covers the admitted
    worst case (the deadlock-freedom invariant, now under preemption)."""
    pool = eng.pool
    holders = Counter(p for r in eng._slots if r is not None
                      for p in r._pages)
    free = set(pool._free)
    assert NULL_PAGE not in free and holders.get(NULL_PAGE, 0) == 0
    for p in range(1, pool.num_pages):
        assert pool.refcounts[p] == holders.get(p, 0)
        if p in free:
            assert pool.refcounts[p] == 0 and p not in pool.cached
    cached_idle = sum(1 for p in pool.cached if pool.refcounts[p] == 0)
    assert len(holders) + pool.num_free + cached_idle == pool.num_pages - 1
    assert pool.num_available >= eng._committed + eng.page_watermark
    # queued requests (incl. preempted ones) hold nothing
    for r in eng._queue:
        assert not r._pages and r._slot == -1


def test_ledger_and_page_invariants_under_preemption_churn(toy_model):
    """Priority traffic through a tight pool with forced + policy-driven
    preemptions: the ledger and page-state invariants hold at every step
    and the pool drains whole."""
    cfg, params = toy_model
    eng = _engine(cfg, params, max_slots=2, page_size=16, num_pages=17,
                  sched_policy="priority", page_watermark=1)
    rng = np.random.default_rng(3)
    reqs = [eng.submit(_prompt(int(rng.integers(8, 40)), i),
                       int(rng.integers(4, 24)),
                       priority=int(rng.integers(0, 3)), seed=i, **GKW)
            for i in range(10)]
    steps = 0
    while True:
        n = eng.step()
        _assert_invariants(eng)
        # force extra churn: preempt a random decoder every few steps
        if steps % 7 == 3:
            decoding = [r for r in eng._slots
                        if r is not None and r._phase == "decode"]
            if decoding:
                eng.preempt(decoding[0])
                _assert_invariants(eng)
        steps += 1
        if n == 0 and not eng._queue:
            break
        assert steps < 5000
    for r in reqs:
        toks, _ = r.result(timeout=5)
        assert len(r.generated) == r.max_new_tokens
    assert eng.preemptions >= 1
    assert int(eng.pool.refcounts.sum()) == 0
    assert eng._committed == 0
    assert eng.pool.num_free + len(eng.pool.cached) == eng.pool.num_pages - 1


# ---------------------------------------------------------------------------
# priority: ordering, aging bound, preemption value rule
# ---------------------------------------------------------------------------


def _fake_req(prio=1, submitted=0.0, seqno=0, generated=0, t_first=0.0,
              ttft_ms=None, tpot_ms=None):
    class R:
        pass

    r = R()
    r.priority = prio
    r.ttft_deadline_ms = ttft_ms
    r.tpot_deadline_ms = tpot_ms
    r.return_log_probs = False
    r.generated = [0] * generated
    r._t_submit = submitted
    r._t_first = t_first
    r._step = generated
    r._seqno = seqno
    return r


def _state(now=100.0, **kw):
    kw.setdefault("ema_tick_s", None)
    kw.setdefault("ema_retire_s", None)
    kw.setdefault("free_slots", 0)
    kw.setdefault("queue_depth", 0)
    kw.setdefault("can_preempt", True)
    return SchedulerState(now=now, **kw)


def test_priority_aging_bound_in_ordering():
    """A class-p request outranks fresh class-0 arrivals after waiting at
    most p * aging_s seconds — the starvation bound, deterministically."""
    pol = PriorityPolicy(aging_s=5.0)
    old_low = _fake_req(prio=3, submitted=0.0, seqno=1)
    # before the bound (waited 10s < 3 * 5s): a just-arrived class-0 wins
    fresh_hi = _fake_req(prio=0, submitted=10.0, seqno=2)
    order = pol.admission_order([old_low, fresh_hi], _state(now=10.0))
    assert order[0] is fresh_hi
    # after the bound (waited 16s > 15s): the aged request wins
    fresh_hi = _fake_req(prio=0, submitted=16.0, seqno=3)
    order = pol.admission_order([old_low, fresh_hi], _state(now=16.0))
    assert order[0] is old_low


def test_priority_starvation_bound_end_to_end(toy_model):
    """Engine-level: a low-priority request older than its aging bound
    admits ahead of a fresher high-priority one."""
    cfg, params = toy_model
    eng = _engine(cfg, params, max_slots=1, sched_policy="priority")
    eng.policy.aging_s = 0.02  # 3-class bound = 60ms
    low = eng.submit(_prompt(8), 4, priority=3, seed=1, **GKW)
    time.sleep(0.1)
    hi = eng.submit(_prompt(8, 3), 4, priority=0, seed=2, **GKW)
    _drain(eng, [low, hi])
    assert low._t_first < hi._t_first, "aged request still starved"


def test_priority_preemption_strictly_lower_value(toy_model):
    """A high-priority arrival evicts a lower-priority decoder (slots
    full), the victim resumes and still finishes; equal-priority arrivals
    never preempt (no livelock)."""
    cfg, params = toy_model
    eng = _engine(cfg, params, max_slots=1, sched_policy="priority")
    low = eng.submit(_prompt(20), 40, priority=2, seed=1, **GKW)
    while len(low.generated) < 5:
        eng.step()
    peer = eng.submit(_prompt(20, 3), 4, priority=2, seed=2, **GKW)
    for _ in range(4):
        eng.step()
    assert eng.preemptions == 0, "equal priority must not preempt"
    hi = eng.submit(_prompt(20, 9), 4, priority=0, seed=3, **GKW)
    for _ in range(4):
        eng.step()
    assert eng.preemptions == 1
    assert low._preemptions == 1
    _drain(eng, [low, peer, hi])
    assert len(low.generated) == 40
    assert hi._t_first < peer._t_first  # hi jumped the aged-equal queue


# ---------------------------------------------------------------------------
# slo: EDF order, shedding, victim rule
# ---------------------------------------------------------------------------


def test_slo_edf_admission_order(toy_model):
    """One slot, three deadlined requests submitted out of deadline
    order: first tokens land earliest-deadline-first, best-effort last."""
    cfg, params = toy_model
    eng = _engine(cfg, params, max_slots=1, sched_policy="slo")
    c = eng.submit(_prompt(8, 2), 3, ttft_deadline_ms=50000, seed=3, **GKW)
    be = eng.submit(_prompt(8, 9), 3, seed=4, **GKW)  # no deadline
    a = eng.submit(_prompt(8, 0), 3, ttft_deadline_ms=10000, seed=1, **GKW)
    b = eng.submit(_prompt(8, 1), 3, ttft_deadline_ms=20000, seed=2, **GKW)
    _drain(eng, [a, b, c, be])
    assert a._t_first < b._t_first < c._t_first < be._t_first


def test_slo_sheds_unmeetable_deadline(toy_model):
    """A queued request whose TTFT deadline already passed is shed with a
    retryable RequestShed instead of wasting pool pages; live-deadline
    traffic is untouched."""
    cfg, params = toy_model
    eng = _engine(cfg, params, max_slots=1, sched_policy="slo")
    dead = eng.submit(_prompt(16), 4, ttft_deadline_ms=0.01, seed=1, **GKW)
    time.sleep(0.05)
    ok = eng.submit(_prompt(16, 3), 4, ttft_deadline_ms=60000, seed=2,
                    **GKW)
    eng.run_until_idle()
    with pytest.raises(RequestShed, match="deadline already passed"):
        dead.result(timeout=5)
    assert dead.shed and dead.shed_retry_after >= 1.0
    ok.result(timeout=60)
    assert eng.shed_requests == 1
    assert eng.scheduler_stats()["shed"] == 1


def test_slo_sheds_on_predicted_queue_wait():
    """Policy-level: with a retirement EMA, a deadline that the predicted
    EDF queue wait overshoots is shed before it ever holds pages."""
    pol = SloPolicy()
    # EDF positions 0 and 1; 2s per retirement
    near = _fake_req(submitted=0.0, seqno=1, ttft_ms=10000)
    tight = _fake_req(submitted=0.0, seqno=2, ttft_ms=11000)
    st = _state(now=10.0, ema_retire_s=2.0)
    shed = pol.shed([near, tight], st)
    # near: eta position 0 -> meets; tight: position 1 -> 10+2 > 11 miss
    assert [(r is tight) for r, _ in shed] == [True]
    assert "predicted queue wait" in shed[0][1]
    # best-effort requests never shed
    assert pol.shed([_fake_req(seqno=3)], st) == []


def test_slo_victim_rule():
    """Preemption victims: best-effort decoders first (inf obligation);
    a candidate without a deadline preempts nobody."""
    pol = SloPolicy()
    cand = _fake_req(seqno=1, ttft_ms=1000, submitted=99.0)
    be_decoder = _fake_req(seqno=2, generated=5, t_first=90.0)
    tight_decoder = _fake_req(seqno=3, generated=5, t_first=90.0,
                              tpot_ms=1.0)
    st = _state(now=100.0)
    assert pol.preempt_victim(cand, [be_decoder, tight_decoder],
                              st) is be_decoder
    no_dl = _fake_req(seqno=4)
    assert pol.preempt_victim(no_dl, [be_decoder], st) is None
    # a decoding request keeps its TTFT deadline as its value: a later
    # arrival from the same burst (later deadline) cannot bounce it —
    # no same-class preemption churn
    same_burst = _fake_req(seqno=5, ttft_ms=1000, submitted=98.0,
                           generated=3, t_first=98.5)
    assert pol.preempt_victim(cand, [same_burst], st) is None


# ---------------------------------------------------------------------------
# Admission control: EMA Retry-After, quotas, centralized queue gauges
# ---------------------------------------------------------------------------


def test_retry_after_from_ema_drain(toy_model):
    """EngineOverloaded.retry_after = queue depth x the EMA retirement
    interval (clamped to [1, 60]) — measured, not the old constant —
    and the structured info rides into the server's 503 body."""
    cfg, params = toy_model
    eng = _engine(cfg, params, max_slots=1, max_queue=3)
    eng._ema_retire_s = 2.5
    for i in range(3):
        eng.submit(_prompt(8, i), 2, seed=i, **GKW)
    with pytest.raises(EngineOverloaded) as ei:
        eng.submit(_prompt(8, 9), 2, **GKW)
    assert ei.value.retry_after == pytest.approx(3 * 2.5)
    assert ei.value.info["queued"] == 3
    assert ei.value.info["policy"] == "fcfs"
    eng.run_until_idle()
    # clamps: no signal -> 1.0; huge backlog -> 60
    assert _engine(cfg, params)._drain_eta(5) == 1.0
    eng._ema_retire_s = 100.0
    assert eng._drain_eta(5) == 60.0


def test_server_503_body_carries_drain_estimate():
    """server.handle_request spreads EngineOverloaded.info into the 503
    body alongside retry_after (the Retry-After header source)."""

    class StuffedEngine:
        lock = None

        def submit(self, *a, **kw):
            raise EngineOverloaded("request queue full (3 waiting)",
                                   retry_after=7.5,
                                   info={"queued": 3, "policy": "slo"})

        def generate_and_post_process(self, *a, **kw):
            self.submit()

        def start(self):
            pass

        def stop(self):
            pass

    srv = MegatronServer(StuffedEngine())
    code, body = srv.handle_request({"prompts": ["x"],
                                     "tokens_to_generate": 4})
    assert code == 503
    assert body["retry_after"] == 7.5
    assert body["queued"] == 3 and body["policy"] == "slo"


def test_server_maps_shed_to_503():
    class SheddingEngine:
        def submit(self, *a, **kw):
            pass

        def generate_and_post_process(self, *a, **kw):
            raise RequestShed("request shed: ttft deadline already passed",
                              retry_after=2.0)

        def start(self):
            pass

        def stop(self):
            pass

    srv = MegatronServer(SheddingEngine())
    code, body = srv.handle_request({"prompts": ["x"],
                                     "tokens_to_generate": 4})
    assert code == 503
    assert body["shed"] is True and body["retry_after"] == 2.0


def test_server_validates_scheduling_fields():
    srv = MegatronServer(object())
    base = {"prompts": ["x"], "tokens_to_generate": 4}
    code, body = srv.handle_request({**base, "priority": "high"})
    assert code == 400 and "priority must be an integer" in body["error"]
    code, body = srv.handle_request({**base, "priority": 11})
    assert code == 400
    code, body = srv.handle_request({**base, "ttft_deadline_ms": -5})
    assert code == 400 and "ttft_deadline_ms" in body["error"]
    code, body = srv.handle_request({**base, "tpot_deadline_ms": True})
    assert code == 400 and "tpot_deadline_ms" in body["error"]


def test_per_priority_queue_bounds(toy_model):
    """--sched_quota bounds each class independently of the global
    bound: an over-quota class 503s while other classes still enqueue."""
    cfg, params = toy_model
    old = cfg.inference.sched_quota
    cfg.inference.sched_quota = "0:2"
    try:
        eng = _engine(cfg, params, max_slots=1, max_queue=16)
    finally:
        cfg.inference.sched_quota = old
    reqs = [eng.submit(_prompt(8, i), 2, priority=0, seed=i, **GKW)
            for i in range(2)]
    with pytest.raises(EngineOverloaded, match="priority-0 queue full"):
        eng.submit(_prompt(8, 9), 2, priority=0, **GKW)
    reqs.append(eng.submit(_prompt(8, 5), 2, priority=1, **GKW))
    _drain(eng, reqs)


def test_queued_gauges_centralized_per_priority(toy_model):
    """mlt_engine_queued_requests carries per-priority labels from the
    single scheduler-owned update point, agrees with the total, and
    drops to zero after the queue drains."""
    cfg, params = toy_model
    reg = obs_registry.get_registry()
    eng = _engine(cfg, params, max_slots=1)
    reqs = [eng.submit(_prompt(8, i), 2, priority=p, seed=i, **GKW)
            for i, p in enumerate((0, 0, 2))]
    total = reg.gauge("mlt_engine_queued_requests").value
    p0 = reg.gauge("mlt_engine_queued_requests",
                   labels={"priority": "0"}).value
    p2 = reg.gauge("mlt_engine_queued_requests",
                   labels={"priority": "2"}).value
    assert total == p0 + p2 and p0 == 2 and p2 == 1
    rendered = reg.render()
    assert 'mlt_engine_queued_requests{priority="0"} 2' in rendered
    _drain(eng, reqs)
    assert reg.gauge("mlt_engine_queued_requests").value == 0
    assert reg.gauge("mlt_engine_queued_requests",
                     labels={"priority": "0"}).value == 0
    assert reg.counter("mlt_engine_preemptions_total").value >= 0


def test_health_scheduler_payload(toy_model):
    cfg, params = toy_model
    eng = _engine(cfg, params, sched_policy="slo")
    srv = MegatronServer(eng)
    info = srv.health()
    sched = info["scheduler"]
    assert sched["policy"] == "slo"
    assert {"queued", "queued_by_priority", "preemptions", "shed",
            "deadline_misses", "retry_after_s"} <= set(sched)


def test_deadline_miss_accounting(toy_model):
    """A retired request that blew its TTFT deadline lands in the miss
    counters (fcfs still serves it; slo would have shed it)."""
    cfg, params = toy_model
    reg = obs_registry.get_registry()
    before = reg.counter("mlt_engine_deadline_miss_total",
                         labels={"kind": "ttft"}).value
    eng = _engine(cfg, params)  # fcfs: never sheds, so the miss retires
    req = eng.submit(_prompt(16), 2, ttft_deadline_ms=0.001, seed=1, **GKW)
    time.sleep(0.01)
    _drain(eng, [req])
    assert eng.deadline_misses == 1
    after = reg.counter("mlt_engine_deadline_miss_total",
                        labels={"kind": "ttft"}).value
    assert after == before + 1
