"""Cross-replica router tests (serving/router/, ISSUE 10).

Four layers, mirroring the subsystem: policy decisions against synthetic
ReplicaViews, the circuit-breaker state machine, the forwarding proxy's
retry/failover/partial-stream semantics against programmable fake
replicas, and an end-to-end 2-replica loopback fleet asserting routed
responses are token-identical to hitting a replica directly.
"""

import dataclasses
import json
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from megatron_llm_tpu.serving.router import (
    DRAINING,
    EJECTED,
    HEALTHY,
    SUSPECT,
    DisaggPolicy,
    FleetOverloaded,
    ForwardingProxy,
    HealthPoller,
    LeastLoadedPolicy,
    PrefixAffinityPolicy,
    Replica,
    ReplicaRegistry,
    ReplicaView,
    RoundRobinPolicy,
    RouteRequest,
    SloAwarePolicy,
)
from megatron_llm_tpu.serving.router.server import RouterServer


def _view(url, *, replica_id=None, seq=1, queued=0, active=0, slots=4,
          ema_retire_ms=None, ema_tick_ms=None, retry_after_s=None,
          fetched_at=None, **extra):
    payload = {
        "replica_id": replica_id or url, "seq": seq, "uptime_s": 1.0,
        "active_slots": active, "max_slots": slots, "queued": queued,
        "scheduler": {"policy": "fcfs", "retry_after_s": retry_after_s,
                      "ema_retire_ms": ema_retire_ms,
                      "ema_tick_ms": ema_tick_ms},
        **extra,
    }
    v = ReplicaView.parse(url, payload)
    if fetched_at is not None:
        v = dataclasses.replace(v, fetched_at=fetched_at)
    return v


REQ = RouteRequest(prefix_text="shared system prompt " * 8)


# ---------------------------------------------------------------------------
# Policy decision matrix
# ---------------------------------------------------------------------------


def test_round_robin_cycles_in_fleet_order():
    views = [_view(f"http://r{i}") for i in range(3)]
    pol = RoundRobinPolicy()
    firsts = [pol.order(REQ, views)[0].url for _ in range(6)]
    assert firsts == ["http://r0", "http://r1", "http://r2"] * 2
    # every order is a permutation of the whole fleet (failover candidates)
    assert sorted(v.url for v in pol.order(REQ, views)) == \
        sorted(v.url for v in views)


def test_least_loaded_scores_depth_times_drain_ema():
    # r0: 6 deep but drains 10ms/req -> 0.06s; r1: 2 deep at 100ms -> 0.2s
    views = [_view("http://r0", queued=4, active=2, ema_retire_ms=10.0),
             _view("http://r1", queued=1, active=1, ema_retire_ms=100.0)]
    assert LeastLoadedPolicy().order(REQ, views)[0].url == "http://r0"
    # raw queue depth would have picked r1 — the drain EMA is load-bearing
    assert views[0].depth > views[1].depth


def test_least_loaded_without_timing_falls_back_to_depth():
    views = [_view("http://r0", queued=3), _view("http://r1", queued=1)]
    assert LeastLoadedPolicy().order(REQ, views)[0].url == "http://r1"


def test_least_loaded_ties_break_on_kv_byte_headroom():
    """Mixed-dtype fleets compare BYTE headroom, not page counts: an
    int8 replica's free page holds half a bf16 replica's (ISSUE 13/19).
    Here the int8 replica has MORE free pages but FEWER free bytes."""
    bf16 = _view("http://bf16", free_pages=10, total_pages=20,
                 kv_pool_bytes=40 << 20)   # 2 MB/page -> 20 MB free
    int8 = _view("http://int8", free_pages=15, total_pages=20,
                 kv_pool_bytes=20 << 20)   # 1 MB/page -> 15 MB free
    order = LeastLoadedPolicy().order(REQ, [int8, bf16])
    assert [v.url for v in order] == ["http://bf16", "http://int8"]
    # replicas predating the byte budget tie-break on raw page counts
    old = [_view("http://a", free_pages=3), _view("http://b", free_pages=9)]
    assert LeastLoadedPolicy().order(REQ, old)[0].url == "http://b"


def test_disagg_orders_decode_then_unified_then_prefill():
    views = [_view("http://p", role="prefill"), _view("http://u"),
             _view("http://d", role="decode")]
    assert [v.url for v in DisaggPolicy().order(REQ, views)] == \
        ["http://d", "http://u", "http://p"]


def test_disagg_degrades_to_least_loaded_on_roleless_fleet():
    views = [_view("http://r0", queued=3), _view("http://r1", queued=1)]
    assert [v.url for v in DisaggPolicy().order(REQ, views)] == \
        ["http://r1", "http://r0"]


def test_disagg_prefill_candidates_gates():
    """The prefill hop is spent only on single-prompt, non-logprobs
    requests past the length threshold, and only when the fleet holds
    BOTH roles — every other shape routes exactly like least_loaded."""
    pol = DisaggPolicy(long_prompt_chars=64)
    long_req = RouteRequest(prefix_text="x" * 100)
    pre = _view("http://p", role="prefill")
    dec = _view("http://d", role="decode")
    assert [v.url for v in pol.prefill_candidates(long_req, [pre, dec])] \
        == ["http://p"]
    assert pol.prefill_candidates(
        RouteRequest(prefix_text="short"), [pre, dec]) == []
    assert pol.prefill_candidates(
        RouteRequest(prefix_text="x" * 100, logprobs=True),
        [pre, dec]) == []
    assert pol.prefill_candidates(
        RouteRequest(prefix_text="x" * 100, n_prompts=2), [pre, dec]) == []
    assert pol.prefill_candidates(long_req, [pre, _view("http://u")]) == []
    assert pol.prefill_candidates(long_req, [dec, _view("http://u")]) == []


def test_prefix_affinity_is_stable_and_order_independent():
    views = [_view(f"http://r{i}", replica_id=f"id{i}") for i in range(4)]
    pol = PrefixAffinityPolicy()
    chosen = pol.order(REQ, views)[0].url
    # stable across calls AND across fleet-list permutations (consistent
    # hashing on replica_id, not list position)
    assert pol.order(REQ, views)[0].url == chosen
    assert pol.order(REQ, list(reversed(views)))[0].url == chosen


def test_prefix_affinity_spreads_distinct_prefixes():
    views = [_view(f"http://r{i}", replica_id=f"id{i}") for i in range(4)]
    pol = PrefixAffinityPolicy()
    targets = {pol.order(RouteRequest(prefix_text=f"prompt family {i} " * 9),
                         views)[0].url for i in range(32)}
    assert len(targets) >= 2, "32 distinct prefixes all hashed to one replica"


def test_prefix_affinity_key_horizon_ignores_tails():
    views = [_view(f"http://r{i}", replica_id=f"id{i}") for i in range(4)]
    pol = PrefixAffinityPolicy(prefix_chars=64)
    shared = "x" * 64
    urls = {pol.order(RouteRequest(prefix_text=shared + tail), views)[0].url
            for tail in ("", "A" * 100, "B" * 500)}
    assert len(urls) == 1, "tails beyond the key horizon changed the route"


def test_prefix_affinity_bounded_load_spills_hot_replica():
    views = [_view(f"http://r{i}", replica_id=f"id{i}") for i in range(3)]
    pol = PrefixAffinityPolicy()
    hot_url = pol.order(REQ, views)[0].url
    # pile a backlog onto the ring choice; everyone else is idle
    loaded = [_view(v.url, replica_id=v.replica_id,
                    queued=8 if v.url == hot_url else 0,
                    active=4 if v.url == hot_url else 0)
              for v in views]
    order = pol.order(REQ, loaded)
    assert order[0].url != hot_url, "hot prefix did not spill"
    assert order[1].url == hot_url, "ring choice should stay second"


def test_prefix_affinity_no_spill_below_bound():
    views = [_view(f"http://r{i}", replica_id=f"id{i}") for i in range(3)]
    pol = PrefixAffinityPolicy()
    hot_url = pol.order(REQ, views)[0].url
    # one queued request is within min_headroom of the idle mean: no spill
    loaded = [_view(v.url, replica_id=v.replica_id,
                    queued=1 if v.url == hot_url else 0) for v in views]
    assert pol.order(REQ, loaded)[0].url == hot_url


def test_slo_aware_picks_fastest_feasible():
    views = [_view("http://slow", queued=8, active=4, ema_retire_ms=500.0,
                   retry_after_s=4.0),
             _view("http://fast", queued=0, active=1, ema_tick_ms=20.0)]
    req = RouteRequest(prefix_text="x", ttft_deadline_ms=500.0)
    order = SloAwarePolicy().order(req, views)
    assert order[0].url == "http://fast"
    assert [v.url for v in order] == ["http://fast", "http://slow"]


def test_slo_aware_sheds_with_fleet_min_retry_after():
    views = [_view("http://a", queued=8, active=4, retry_after_s=9.0),
             _view("http://b", queued=8, active=4, retry_after_s=3.0)]
    req = RouteRequest(prefix_text="x", ttft_deadline_ms=100.0)
    with pytest.raises(FleetOverloaded) as ei:
        SloAwarePolicy().order(req, views)
    # the aggregated 503's Retry-After is the SOONEST replica's estimate
    assert ei.value.retry_after == pytest.approx(3.0)
    assert set(ei.value.info["predicted_wait_s"]) == {"http://a", "http://b"}


def test_slo_aware_without_deadline_degrades_to_least_loaded():
    views = [_view("http://a", queued=5, ema_retire_ms=100.0),
             _view("http://b", queued=1, ema_retire_ms=100.0)]
    req = RouteRequest(prefix_text="x")
    assert SloAwarePolicy().order(req, views)[0].url == "http://b"


# ---------------------------------------------------------------------------
# Circuit-breaker state machine
# ---------------------------------------------------------------------------


def test_breaker_failure_ladder_and_recovery():
    rep = Replica("http://r", suspect_after=1, eject_after=3)
    rep.record_view(_view("http://r"))
    assert rep.state == HEALTHY and rep.routable(None)
    assert rep.record_failure("boom") == SUSPECT
    assert rep.routable(None), "suspect replicas still route"
    rep.record_failure("boom")
    assert rep.state == SUSPECT
    assert rep.record_failure("boom") == EJECTED
    assert not rep.routable(None)
    # recovery probe succeeds -> straight back to healthy, failures reset
    rep.record_view(_view("http://r", seq=2))
    assert rep.state == HEALTHY and rep.summary()["consecutive_failures"] == 0


def test_breaker_drain_is_operator_sticky():
    rep = Replica("http://r")
    rep.record_view(_view("http://r"))
    rep.drain(True)
    assert rep.state == DRAINING and not rep.routable(None)
    # successful polls keep refreshing the view but cannot undrain
    rep.record_view(_view("http://r", seq=2))
    assert rep.state == DRAINING
    # failures while draining don't flap the state either
    rep.record_failure("boom")
    assert rep.state == DRAINING
    rep.drain(False)
    # undrain re-enters through the breaker using the failure count
    assert rep.state == SUSPECT
    rep.record_view(_view("http://r", seq=3))
    assert rep.state == HEALTHY


def test_breaker_detects_restart_by_replica_id():
    rep = Replica("http://r")
    rep.record_view(_view("http://r", replica_id="proc1", seq=100))
    # same url, new process: fresh id, seq starts over — accepted
    assert rep.record_view(_view("http://r", replica_id="proc2", seq=1))
    s = rep.summary()
    assert s["restarts"] == 1 and s["seq"] == 1


def test_breaker_discards_stale_and_reordered_payloads():
    rep = Replica("http://r")
    rep.record_view(_view("http://r", replica_id="p", seq=5, queued=7))
    assert not rep.record_view(_view("http://r", replica_id="p", seq=4,
                                     queued=0)), "older seq must not apply"
    assert not rep.record_view(_view("http://r", replica_id="p", seq=5))
    assert rep.view.queued == 7
    assert rep.summary()["stale_discards"] == 2
    assert rep.record_view(_view("http://r", replica_id="p", seq=6))


def test_staleness_gates_routability():
    rep = Replica("http://r")
    old = time.monotonic() - 99.0
    rep.record_view(_view("http://r", fetched_at=old))
    assert rep.routable(None), "no staleness bound -> any view routes"
    assert not rep.routable(10.0), "stale view must not route"


def test_poller_drives_breaker_and_registry_views():
    calls = {"n": 0}

    def fetch(url, timeout):
        calls["n"] += 1
        if calls["n"] <= 3:
            raise ConnectionError("down")
        return {"replica_id": "p", "seq": calls["n"], "active_slots": 1,
                "max_slots": 4}

    registry = ReplicaRegistry(["http://r"], eject_after=3)
    poller = HealthPoller(registry, fetch=fetch)
    rep = registry.get("http://r")
    for expect in (SUSPECT, SUSPECT, EJECTED):
        assert not poller.poll_once(rep)
        assert rep.state == expect
    assert registry.routable_views() == []
    assert poller.poll_once(rep)  # recovery probe
    assert rep.state == HEALTHY
    assert [v.url for v in registry.routable_views()] == ["http://r"]


# ---------------------------------------------------------------------------
# Forwarding proxy semantics (programmable fake replicas)
# ---------------------------------------------------------------------------


class _FakeReplica:
    """Minimal /api + /health replica with a programmable script.

    ``script`` entries per request: ("ok", body) | ("503", retry_after)
    | ("partial",).  Past the script's end it keeps answering "ok"."""

    def __init__(self, script=()):
        self.script = list(script)
        self.requests = 0
        self.health_polls = 0
        outer = self

        class H(BaseHTTPRequestHandler):
            def do_PUT(self):
                outer.requests += 1
                step = (outer.script[outer.requests - 1]
                        if outer.requests <= len(outer.script) else ("ok",))
                if step[0] == "503":
                    body = json.dumps({"error": "queue full",
                                       "retry_after": step[1]}).encode()
                    self.send_response(503)
                    self.send_header("Retry-After",
                                     str(max(1, int(step[1]))))
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if step[0] == "partial":
                    # promise 1000 bytes, deliver 10, FIN: response-phase
                    # failure after the request was accepted
                    self.send_response(200)
                    self.send_header("Content-Length", "1000")
                    self.end_headers()
                    self.wfile.write(b'{"text": [')
                    self.wfile.flush()
                    self.connection.shutdown(socket.SHUT_WR)
                    return
                body = json.dumps({"text": ["ok"], "served_by": outer.url,
                                   "n": outer.requests}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                outer.health_polls += 1
                body = json.dumps({
                    "status": "ok", "replica_id": outer.url,
                    "seq": outer.health_polls, "uptime_s": 1.0,
                    "active_slots": 0, "max_slots": 4, "queued": 0,
                }).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _dead_url():
    """A url nothing listens on (bind, grab the port, close)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"http://127.0.0.1:{port}"


BODY = json.dumps({"prompts": ["hi"], "tokens_to_generate": 1}).encode()


def test_proxy_failover_excludes_connect_failed_replica():
    dead = _dead_url()
    live = _FakeReplica()
    try:
        registry = ReplicaRegistry([dead, live.url])
        out = ForwardingProxy(registry, timeout_s=5.0).forward(
            [dead, live.url], BODY)
        assert out.status == 200
        assert json.loads(out.body)["served_by"] == live.url
        assert out.failovers == 1 and out.retries == 0
        # the data-plane failure fed the breaker without waiting for a poll
        assert registry.get(dead).state == SUSPECT
    finally:
        live.stop()


def test_proxy_honors_retry_after_then_succeeds():
    rep = _FakeReplica(script=[("503", 2.0), ("ok",)])
    slept = []
    try:
        registry = ReplicaRegistry([rep.url])
        proxy = ForwardingProxy(registry, timeout_s=5.0,
                                sleep=slept.append)
        out = proxy.forward([rep.url], BODY)
        assert out.status == 200 and out.retries == 1
        assert slept == [2.0], "must sleep the replica's Retry-After"
    finally:
        rep.stop()


def test_proxy_bounded_retries_then_aggregated_503():
    rep = _FakeReplica(script=[("503", 2.0)] * 10)
    slept = []
    try:
        registry = ReplicaRegistry([rep.url])
        proxy = ForwardingProxy(registry, timeout_s=5.0, max_retries=2,
                                sleep=slept.append)
        out = proxy.forward([rep.url], BODY)
        assert out.status == 503
        assert rep.requests == 3, "1 walk + max_retries rounds, no more"
        body = json.loads(out.body)
        assert body["fleet_saturated"] is True
        assert out.retry_after == pytest.approx(2.0)
    finally:
        rep.stop()


def test_proxy_backoff_cap_bounds_long_retry_after():
    rep = _FakeReplica(script=[("503", 60.0), ("ok",)])
    slept = []
    try:
        proxy = ForwardingProxy(ReplicaRegistry([rep.url]), timeout_s=5.0,
                                backoff_cap_s=0.05, sleep=slept.append)
        assert proxy.forward([rep.url], BODY).status == 200
        assert slept == [0.05]
    finally:
        rep.stop()


def test_proxy_never_retries_partial_response():
    """A response that dies mid-body is non-idempotent: exactly one
    upstream request, a structured 502, no failover to the healthy twin."""
    partial = _FakeReplica(script=[("partial",)])
    healthy = _FakeReplica()
    try:
        registry = ReplicaRegistry([partial.url, healthy.url])
        out = ForwardingProxy(registry, timeout_s=5.0).forward(
            [partial.url, healthy.url], BODY)
        assert out.status == 502
        assert b"not retried" in out.body
        assert partial.requests == 1
        assert healthy.requests == 0, "partial stream must not fail over"
    finally:
        partial.stop()
        healthy.stop()


def test_proxy_forwards_4xx_verbatim_without_failover():
    rep = _FakeReplica()
    other = _FakeReplica()

    # patch the first replica to 400 every request
    def do_put(handler):
        rep.requests += 1
        body = json.dumps({"error": "prompts is empty"}).encode()
        handler.send_response(400)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    rep.httpd.RequestHandlerClass.do_PUT = do_put
    try:
        out = ForwardingProxy(
            ReplicaRegistry([rep.url, other.url]), timeout_s=5.0
        ).forward([rep.url, other.url], BODY)
        assert out.status == 400
        assert other.requests == 0, "client errors are terminal fleet-wide"
    finally:
        rep.stop()
        other.stop()


# ---------------------------------------------------------------------------
# RouterServer endpoints against fake replicas
# ---------------------------------------------------------------------------


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read()


def _put(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="PUT")
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_router_server_routes_health_metrics_and_drain():
    reps = [_FakeReplica(), _FakeReplica()]
    router = RouterServer([r.url for r in reps], policy="round_robin",
                          poll_interval=30.0)  # warm poll only
    try:
        port = router.start_background()
        base = f"http://127.0.0.1:{port}"

        # routing: round_robin alternates replicas
        served = [_put(base + "/api", {"prompts": ["hi"],
                                       "tokens_to_generate": 1})[1]
                  ["served_by"] for _ in range(4)]
        assert served[0] != served[1] and served[:2] == served[2:]

        # fleet /health summary
        status, body = _get(base + "/health")
        info = json.loads(body)
        assert info["role"] == "router" and info["policy"] == "round_robin"
        assert info["routable"] == 2 and len(info["replicas"]) == 2
        assert all(r["state"] == HEALTHY for r in info["replicas"])
        assert all(r["replica_id"] for r in info["replicas"])

        # /metrics exposition
        status, body = _get(base + "/metrics")
        text = body.decode()
        assert "mlt_router_replica_up" in text
        assert "mlt_router_decisions_total" in text
        assert "mlt_router_ttft_seconds_bucket" in text

        # operator drain: no new traffic to the drained replica
        target = reps[0].url
        req = urllib.request.Request(
            base + "/admin/drain",
            data=json.dumps({"replica": target}).encode(), method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert json.loads(resp.read())["state"] == DRAINING
        before = reps[0].requests
        for _ in range(4):
            code, body = _put(base + "/api", {"prompts": ["hi"],
                                              "tokens_to_generate": 1})
            assert code == 200 and body["served_by"] != target
        assert reps[0].requests == before

        # undrain restores it
        req = urllib.request.Request(
            base + "/admin/undrain",
            data=json.dumps({"replica": target}).encode(), method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert json.loads(resp.read())["state"] == HEALTHY
    finally:
        router.stop()
        for r in reps:
            r.stop()


def test_router_server_503_when_no_replica_routable():
    dead = _dead_url()
    router = RouterServer([dead], poll_interval=30.0)
    try:
        port = router.start_background()
        code, body = _put(f"http://127.0.0.1:{port}/api",
                          {"prompts": ["hi"], "tokens_to_generate": 1})
        assert code == 503 and "no routable replica" in body["error"]
        assert body["retry_after"] >= 1.0
    finally:
        router.stop()


def test_router_server_slo_shed_is_structured_503():
    rep = _FakeReplica()
    try:
        router = RouterServer([rep.url], policy="slo_aware",
                              poll_interval=30.0)
        port = router.start_background()
        # poison the view with a hopeless backlog, then ask for 1ms TTFT
        router.registry.get(rep.url).record_view(
            _view(rep.url, seq=999, queued=50, active=4, retry_after_s=8.0))
        code, body = _put(f"http://127.0.0.1:{port}/api",
                          {"prompts": ["hi"], "tokens_to_generate": 1,
                           "ttft_deadline_ms": 1.0})
        assert code == 503 and body["shed"] is True
        assert body["retry_after"] >= 1.0
        assert rep.requests == 0, "shed requests must not reach replicas"
    finally:
        router.stop()
        rep.stop()


# ---------------------------------------------------------------------------
# End-to-end: 2-replica loopback fleet over real engines
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet():
    """Two continuous-batching replicas sharing identical weights, behind
    real MegatronServers on ephemeral ports (--port 0 semantics)."""
    import jax

    from megatron_llm_tpu.generation import ContinuousBatchingEngine
    from megatron_llm_tpu.generation.server import MegatronServer
    from megatron_llm_tpu.models import init_model_params, make_config
    from tests.test_generation import VOCAB, ToyTokenizer

    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, ffn_hidden_size=128, seq_length=128,
        max_position_embeddings=256, vocab_size=VOCAB,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype="float32", use_flash_attn=False,
    )
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    servers, urls = [], []
    for _ in range(2):
        engine = ContinuousBatchingEngine(cfg, params, ToyTokenizer(),
                                          max_slots=4, max_seq=128)
        srv = MegatronServer(engine)
        port = srv.start_background(port=0)
        servers.append(srv)
        urls.append(f"http://127.0.0.1:{port}")
    yield servers, urls
    for srv in servers:
        try:
            srv.stop()
        except Exception:
            pass


def test_replica_health_carries_router_identity_fields(fleet):
    """ISSUE 10 satellite: /health gains replica_id (stable per process),
    seq (monotonic), uptime_s, page_size."""
    _, urls = fleet
    _, b1 = _get(urls[0] + "/health")
    _, b2 = _get(urls[0] + "/health")
    h1, h2 = json.loads(b1), json.loads(b2)
    for field in ("replica_id", "seq", "uptime_s", "page_size"):
        assert field in h1, f"missing {field}"
    assert h2["replica_id"] == h1["replica_id"]
    assert h2["seq"] > h1["seq"], "seq must be monotonic"
    assert h2["uptime_s"] >= h1["uptime_s"]
    # distinct processes (here: distinct servers) get distinct ids
    _, bo = _get(urls[1] + "/health")
    assert json.loads(bo)["replica_id"] != h1["replica_id"]


GEN = dict(tokens_to_generate=12, top_k=1, logprobs=True)


def test_e2e_routed_responses_token_identical_to_direct(fleet):
    """The acceptance bar: the same greedy request through the router and
    straight at a replica produces identical text/segments/logprobs."""
    _, urls = fleet
    router = RouterServer(urls, policy="round_robin", poll_interval=30.0)
    try:
        port = router.start_background()
        base = f"http://127.0.0.1:{port}"
        for i in range(4):  # alternates replicas under round_robin
            payload = {"prompts": [f"route me {i} please"], **GEN}
            code, routed = _put(base + "/api", payload)
            assert code == 200
            direct = [_put(u + "/api", payload)[1] for u in urls]
            # the timing block (ISSUE 12) is per-serve metadata — wall
            # clocks and trace ids legitimately differ per request; the
            # generation payload must not
            for b in (routed, *direct):
                assert b.pop("timing", None) is not None
            assert routed == direct[0] == direct[1], (
                "routing changed the tokens")
    finally:
        router.stop()


def test_e2e_failover_mid_fleet_zero_dropped(fleet):
    """Kill one replica (listening socket down — new connections refused),
    then push traffic: every request succeeds via failover, the breaker
    ejects the dead replica, and answers stay token-identical."""
    import jax

    from megatron_llm_tpu.generation import ContinuousBatchingEngine
    from megatron_llm_tpu.generation.server import MegatronServer
    from tests.test_generation import ToyTokenizer

    servers, urls = fleet
    # a sacrificial third replica so the module fleet survives this test
    eng = servers[0].engine
    victim_srv = MegatronServer(ContinuousBatchingEngine(
        eng.cfg, eng.params, ToyTokenizer(), max_slots=4, max_seq=128))
    vport = victim_srv.start_background(port=0)
    victim = f"http://127.0.0.1:{vport}"
    router = RouterServer([victim, urls[0]], policy="round_robin",
                          poll_interval=30.0, eject_after=2)
    try:
        port = router.start_background()
        base = f"http://127.0.0.1:{port}"
        payload = {"prompts": ["failover determinism probe"], **GEN}
        code, before = _put(base + "/api", payload)
        assert code == 200
        before.pop("timing", None)  # per-serve metadata (ISSUE 12)
        victim_srv.stop()  # refuse new connections from here on
        results = [None] * 6

        def worker(i):
            code_i, body_i = _put(base + "/api", payload)
            if isinstance(body_i, dict):
                body_i.pop("timing", None)  # per-serve metadata
            results[i] = (code_i, body_i)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(len(results))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(code == 200 for code, _ in results), (
            f"dropped requests during failover: "
            f"{[c for c, _ in results if c != 200]}")
        assert all(body == before for _, body in results), (
            "failover changed the tokens")
        assert router.registry.get(victim).state == EJECTED
        assert router.registry.get(urls[0]).state == HEALTHY
    finally:
        router.stop()


def test_e2e_prefix_affinity_colocates_shared_prefix(fleet):
    """Requests sharing a system prompt all land on one replica (the other
    replica's engine never ticks), and that replica's prefix cache serves
    the shared pages."""
    servers, urls = fleet
    router = RouterServer(urls, policy="prefix_affinity",
                          policy_kwargs=dict(prefix_chars=64),
                          poll_interval=30.0)
    try:
        port = router.start_background()
        base = f"http://127.0.0.1:{port}"
        shared = "fleet shared system prompt " * 4  # > prefix_chars horizon
        engines = [s.engine for s in servers]
        ticks0 = [e.ticks for e in engines]
        hits0 = [e.prefix_hit_tokens for e in engines]
        for i in range(5):
            # logprobs requests skip prefix matching by design (PR 5), so
            # this workload decodes plain greedy
            code, _ = _put(base + "/api",
                           {"prompts": [shared + f" tail {i}"],
                            "tokens_to_generate": 12, "top_k": 1})
            assert code == 200
        ticked = [e.ticks - t0 for e, t0 in zip(engines, ticks0)]
        assert sorted(ticked)[0] == 0, (
            f"shared-prefix traffic split across replicas: {ticked}")
        hit_gain = [e.prefix_hit_tokens - h0
                    for e, h0 in zip(engines, hits0)]
        assert max(hit_gain) > 0, "co-located requests never hit the cache"
    finally:
        router.stop()


def test_server_tool_port_zero_prints_bound_port():
    """ISSUE 10 satellite: ``run_text_generation_server.py --port 0``
    binds an ephemeral port and prints it on startup — the fleet-spawning
    contract (parse the line, then poll /health)."""
    import os
    import re
    import signal
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.Popen(
        [sys.executable, "tools/run_text_generation_server.py",
         "--random_init", "--port", "0", "--host", "127.0.0.1",
         "--tokenizer_type", "NullTokenizer", "--vocab_size", "128",
         "--num_layers", "1", "--hidden_size", "32",
         "--num_attention_heads", "2", "--ffn_hidden_size", "64",
         "--seq_length", "64", "--max_position_embeddings", "64"],
        cwd=repo, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        port = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            m = re.search(r"on http://127\.0\.0\.1:(\d+)/api", line)
            if m:
                port = int(m.group(1))
                break
        assert port is not None, "server never printed its bound port"
        assert port != 0
        _, body = _get(f"http://127.0.0.1:{port}/health")
        info = json.loads(body)
        assert info["status"] == "ok"
        assert info["replica_id"] and info["seq"] >= 1
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)


def test_run_router_tool_parses_and_requires_replicas():
    """tools/run_router.py wires flags to the server (no sockets here —
    argparse-level contract)."""
    import tools.run_router as rr

    with pytest.raises(SystemExit):
        rr.main(["--policy", "least_loaded"])  # no replicas
    with pytest.raises(SystemExit):
        rr.main(["--replica", "http://x", "--policy", "nonsense"])
