"""REST server contract tests (reference analog: none — the reference server
is untested; we gate on the documented wire contract of
text_generation_server.py: PUT /api validation messages and response keys)."""

import json
import urllib.request

import jax
import pytest

from megatron_llm_tpu.generation import InferenceEngine
from megatron_llm_tpu.generation.server import MegatronServer, _validate
from megatron_llm_tpu.models import init_model_params, make_config

from tests.test_generation import VOCAB, ToyTokenizer


@pytest.fixture(scope="module")
def server():
    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, ffn_hidden_size=128, seq_length=128,
        max_position_embeddings=256, vocab_size=VOCAB,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype="float32", use_flash_attn=False,
    )
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    engine = InferenceEngine(cfg, params, ToyTokenizer())
    srv = MegatronServer(engine)
    port = srv.start_background(port=0)  # ephemeral port
    yield f"http://127.0.0.1:{port}"
    srv.stop()


def _put(url, payload):
    req = urllib.request.Request(
        url + "/api", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="PUT",
    )
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_validation_messages():
    assert _validate({})[1] == "prompts argument required"
    assert _validate({"prompts": "x"})[1] == "prompts is not a list of strings"
    assert _validate({"prompts": []})[1] == "prompts is empty"
    assert _validate({"prompts": ["a"], "max_len": 3})[1].startswith(
        "max_len is no longer used")
    assert _validate({"prompts": ["a"], "tokens_to_generate": 0})[1] == \
        "tokens_to_generate=0 implies logprobs should be True"
    assert _validate({"prompts": ["a"], "top_k": 3, "top_p": 0.5})[1] == \
        "cannot set both top-k and top-p samplings."
    assert _validate({"prompts": ["a", "b"], "beam_width": 2})[1] == \
        "When doing beam_search, batch size must be 1"
    params, err = _validate({"prompts": ["a"], "tokens_to_generate": 8})
    assert err is None and params["tokens_to_generate"] == 8


def test_server_generate_roundtrip(server):
    status, body = _put(server, {
        "prompts": ["hello"], "tokens_to_generate": 4, "top_k": 1,
        "logprobs": True,
    })
    assert status == 200
    assert set(body) == {"text", "segments", "logprobs"}
    assert len(body["text"]) == 1 and isinstance(body["text"][0], str)
    assert len(body["logprobs"][0]) == len(body["segments"][0]) - 1


def test_server_beam_roundtrip(server):
    status, body = _put(server, {
        "prompts": ["hello"], "tokens_to_generate": 4, "beam_width": 2,
        "stop_token": VOCAB + 9,
    })
    assert status == 200
    assert set(body) == {"text", "segments", "scores"}
    assert len(body["text"]) == 2


def test_server_rejects_bad_request(server):
    status, body = _put(server, {"prompts": []})
    assert status == 400


def test_server_rejects_overlong_request(server):
    """prompt + tokens_to_generate > max_position_embeddings -> 400 with the
    reference's message (generation.py:133-135)."""
    status, body = _put(server, {
        "prompts": ["hello"], "tokens_to_generate": 100000})
    assert status == 400
    assert "longer than allowed" in body


def test_server_serves_ui(server):
    with urllib.request.urlopen(server + "/") as resp:
        assert resp.status == 200
        assert b"Generate" in resp.read()


def test_server_structured_json_errors(server):
    """Errors are {"error": msg} JSON with proper status codes — including
    payloads that are valid JSON but not objects (previously a 500 with a
    bare traceback path)."""
    status, body = _put(server, ["not", "an", "object"])
    assert status == 400
    assert json.loads(body)["error"] == "request body must be a JSON object"

    status, body = _put(server, {"prompts": []})
    assert status == 400
    assert "prompts is empty" in json.loads(body)["error"]

    status, body = _put(server, {"prompts": ["x"], "tokens_to_generate": 10 ** 6})
    assert status == 400
    assert "longer than allowed" in json.loads(body)["error"]

    req = urllib.request.Request(
        server + "/api", data=b"{not json", method="PUT")
    try:
        urllib.request.urlopen(req)
        assert False, "expected 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400
        assert json.loads(e.read().decode())["error"] == "invalid JSON"


# ---------------------------------------------------------------------------
# Continuous-batching server (generation/engine.py behind the same wire)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def batching_server():
    from megatron_llm_tpu.generation import ContinuousBatchingEngine

    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, ffn_hidden_size=128, seq_length=128,
        max_position_embeddings=256, vocab_size=VOCAB,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype="float32", use_flash_attn=False,
    )
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    engine = ContinuousBatchingEngine(cfg, params, ToyTokenizer(),
                                      max_slots=8, max_seq=128)
    srv = MegatronServer(engine)
    port = srv.start_background(port=0)
    yield f"http://127.0.0.1:{port}", engine
    srv.stop()


def test_batching_server_same_wire_contract(batching_server):
    url, _ = batching_server
    status, body = _put(url, {
        "prompts": ["hello"], "tokens_to_generate": 4, "top_k": 1,
        "logprobs": True,
    })
    assert status == 200
    # ISSUE 12 extends the wire contract with server-side timing
    # metadata (trace id, first-token time, latency decomposition)
    assert set(body) == {"text", "segments", "logprobs", "timing"}
    assert body["timing"]["ttft_s"] is not None
    assert len(body["logprobs"][0]) == len(body["segments"][0]) - 1


def test_batching_server_concurrent_requests_share_ticks(batching_server):
    """Concurrent HTTP requests are admitted into shared decode ticks: all
    succeed, and the engine ticked far fewer times than the serialized
    one-tick-per-token count."""
    import threading

    url, engine = batching_server
    ticks0, n, gen_len = engine.ticks, 6, 12
    results = [None] * n

    def worker(i):
        results[i] = _put(url, {
            "prompts": [f"prompt number {i}"], "tokens_to_generate": gen_len,
            "top_k": 1,
        })

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(status == 200 for status, _ in results)
    assert all(len(body["segments"][0]) > 0 for _, body in results)
    # serialized decode would need ~n * gen_len ticks; sharing needs far
    # fewer (admission order may stagger slightly under thread scheduling)
    assert engine.ticks - ticks0 < n * gen_len


def test_batching_server_health_endpoint(batching_server):
    url, _ = batching_server
    with urllib.request.urlopen(url + "/health") as resp:
        assert resp.status == 200
        info = json.loads(resp.read())
    assert info["status"] == "ok" and info["batching"] is True
    assert info["free_pages"] == info["total_pages"]  # idle between tests


def test_batching_server_health_reports_cache_and_queue(batching_server):
    """ISSUE 5: /health carries prefix-cache occupancy and queue depth."""
    url, engine = batching_server
    with urllib.request.urlopen(url + "/health") as resp:
        info = json.loads(resp.read())
    for field in ("pages_cached", "available_pages", "prefix_hit_tokens",
                  "prefix_miss_tokens", "queued", "prefilling"):
        assert field in info, f"missing {field}"
    assert info["pages_cached"] == len(engine.pool.cached)
    assert info["available_pages"] >= info["free_pages"]


def test_server_queue_overflow_returns_503_with_retry_after():
    """ISSUE 5: backpressure is a structured JSON 503 with a Retry-After
    header, not an unbounded queue."""
    from megatron_llm_tpu.generation.engine import EngineOverloaded
    from megatron_llm_tpu.generation.server import MegatronServer

    class StuffedEngine:
        """Duck-typed batching engine whose queue is at capacity."""

        def submit(self, *a, **kw):
            raise EngineOverloaded("request queue full (2 waiting)",
                                   retry_after=3.0)

        def generate_and_post_process(self, *a, **kw):
            return self.submit()

        def start(self):
            pass

        def stop(self):
            pass

    srv = MegatronServer(StuffedEngine())
    code, body = srv.handle_request(
        {"prompts": ["hi"], "tokens_to_generate": 4})
    assert code == 503
    assert "queue full" in body["error"] and body["retry_after"] == 3.0

    port = srv.start_background(port=0)
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api",
            data=json.dumps({"prompts": ["hi"],
                             "tokens_to_generate": 4}).encode(),
            method="PUT")
        try:
            urllib.request.urlopen(req)
            assert False, "expected 503"
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert e.headers["Retry-After"] == "3"
            payload = json.loads(e.read().decode())
            assert "queue full" in payload["error"]
    finally:
        srv.stop()
