"""Instruction/SFT pipeline tests: collator semantics vs the reference
(megatron/data/instruction_dataset.py:377-475), dataset split/sampling,
preprocessing tools, and an end-to-end instruction-tuning run."""

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from megatron_llm_tpu.data.indexed_dataset import make_builder
from megatron_llm_tpu.data.instruction_dataset import (
    Role,
    build_train_valid_test_datasets,
    instruction_collator,
)

REPO = Path(__file__).parent.parent


def make_sample(spans):
    """spans: list of (role_value, length) -> {"text", "role"} arrays."""
    text, role = [], []
    tok = 10
    for r, n in spans:
        text += list(range(tok, tok + n))
        role += [r] * n
        tok += n
    return {"text": np.array(text, dtype=np.int64),
            "role": np.array(role, dtype=np.int64)}


class TestInstructionCollator:
    def test_loss_mask_follows_role(self):
        # 3 system, 4 user, 5 assistant tokens; seq_length 16 → padding after.
        sample = make_sample([(Role.system, 3), (Role.user, 4), (Role.assistant, 5)])
        out = instruction_collator([sample], seq_length=16, pad_id=0)
        # loss only where the *input* token is assistant-role (reference
        # computes the mask on the unshifted buffer then slices [:, :-1]).
        expect = np.zeros(16, np.float32)
        expect[7:12] = 1.0
        np.testing.assert_array_equal(out["loss_mask"][0], expect)
        # padding never contributes loss
        assert out["loss_mask"][0, 12:].sum() == 0

    def test_loss_role_variants(self):
        sample = make_sample([(Role.user, 4), (Role.assistant, 4)])
        user = instruction_collator([sample], 8, pad_id=0, loss_role="user")
        np.testing.assert_array_equal(user["loss_mask"][0, :4], np.ones(4))
        np.testing.assert_array_equal(user["loss_mask"][0, 4:], np.zeros(4))
        all_ = instruction_collator([sample], 8, pad_id=0, loss_role="all")
        assert all_["loss_mask"][0].sum() == 8

    def test_scalar_loss_mask(self):
        # scalar_loss_mask puts a small weight on non-loss-role tokens
        sample = make_sample([(Role.user, 4), (Role.assistant, 4)])
        out = instruction_collator([sample], 8, pad_id=0, scalar_loss_mask=0.1)
        np.testing.assert_allclose(out["loss_mask"][0, :4], 0.1)
        np.testing.assert_allclose(out["loss_mask"][0, 4:], 1.0)

    def test_shift_alignment(self):
        sample = make_sample([(Role.assistant, 6)])
        out = instruction_collator([sample], 8, pad_id=0)
        np.testing.assert_array_equal(out["tokens"][0, :5], sample["text"][:5])
        np.testing.assert_array_equal(out["labels"][0, :5], sample["text"][1:6])

    def test_packed_segments_and_positions(self):
        # two conversations joined by a PACK_SEP token
        sample = make_sample([(Role.user, 3), (Role.PACK_SEP, 1), (Role.assistant, 4)])
        out = instruction_collator([sample], 12, pad_id=0)
        seg = out["segment_ids"][0]
        # first conversation = segment 0; PACK_SEP opens segment 1 (reference
        # :424-433: the sep token belongs to the new example)
        np.testing.assert_array_equal(seg[:3], [0, 0, 0])
        np.testing.assert_array_equal(seg[3:8], [1, 1, 1, 1, 1])
        # padding gets sentinel -1 so real tokens never attend to it
        np.testing.assert_array_equal(seg[8:], [-1, -1, -1, -1])
        # position ids reset at the boundary; PACK_SEP is position 0 of the
        # new example (reference :363-372)
        np.testing.assert_array_equal(out["position_ids"][0, :8],
                                      [0, 1, 2, 0, 1, 2, 3, 4])

    def test_segment_mask_matches_reference_dense_mask(self):
        # reference builds mask[i,j] = causal & same-example & not-padding
        # (:344-361); our segment ids must induce the same dense mask.
        sample = make_sample([(Role.user, 3), (Role.PACK_SEP, 1),
                              (Role.assistant, 3), (Role.PACK_SEP, 1),
                              (Role.user, 2)])
        s = 14
        out = instruction_collator([sample], s, pad_id=0)
        seg = out["segment_ids"][0]
        ours = (seg[:, None] == seg[None, :]) & (seg[:, None] >= 0)
        ours &= np.tril(np.ones((s, s), bool))

        # reference-style dense construction
        n = len(sample["text"])
        example_ids = np.zeros(s, np.int64)
        cur = 0
        for j in range(min(n, s)):
            if sample["role"][j] == Role.PACK_SEP:
                cur += 1
            example_ids[j] = cur
        valid = np.arange(s) < n
        dense = (example_ids[:, None] == example_ids[None, :])
        dense &= np.tril(np.ones((s, s), bool))
        dense &= valid[:, None] & valid[None, :]
        np.testing.assert_array_equal(ours, dense)

    def test_truncation(self):
        sample = make_sample([(Role.assistant, 30)])
        out = instruction_collator([sample], 8, pad_id=0)
        assert out["tokens"].shape == (1, 8)
        np.testing.assert_array_equal(out["tokens"][0], sample["text"][:8])
        np.testing.assert_array_equal(out["labels"][0], sample["text"][1:9])
        assert out["loss_mask"][0].sum() == 8

    def test_variable_seq_lengths(self):
        samples = [make_sample([(Role.assistant, 10)]),
                   make_sample([(Role.assistant, 20)])]
        out = instruction_collator(samples, 512, pad_id=0,
                                   variable_seq_lengths=True)
        # rounded to multiple of 16 >= longest+? (reference rounds the max
        # sample length, then +1 for the shift and -1 back)
        assert out["tokens"].shape == (2, 32)


@pytest.fixture
def instruct_corpus(tmp_path):
    """20 docs of paired text/role streams."""
    prefix = str(tmp_path / "chat")
    rng = np.random.RandomState(1)
    tb = make_builder(prefix + "-text.bin", vocab_size=500)
    rb = make_builder(prefix + "-role.bin", vocab_size=2000)
    for _ in range(20):
        nu, na = rng.randint(5, 15), rng.randint(5, 15)
        tb.add_doc(rng.randint(1, 500, size=nu + na))
        rb.add_doc([int(Role.user)] * nu + [int(Role.assistant)] * na)
    tb.finalize(prefix + "-text.idx")
    rb.finalize(prefix + "-role.idx")
    return prefix


class TestInstructionDataset:
    def test_split_and_sampling(self, instruct_corpus):
        train, valid, test = build_train_valid_test_datasets(
            [instruct_corpus], "80,10,10", (50, 5, 5), seq_length=64, seed=3)
        assert len(train) == 50 and len(valid) == 5 and len(test) == 5
        s = train[0]
        assert s["text"].shape == s["role"].shape
        assert set(np.unique(s["role"])) <= {0, 1, 2, 1000}
        # determinism
        train2, _, _ = build_train_valid_test_datasets(
            [instruct_corpus], "80,10,10", (50, 5, 5), seq_length=64, seed=3)
        np.testing.assert_array_equal(train.sample_indices, train2.sample_indices)
        # train/valid splits index disjoint documents
        assert not (set(train.sample_indices.tolist())
                    & set(valid.sample_indices.tolist()))

    def test_separate_split_paths(self, instruct_corpus):
        train, valid, test = build_train_valid_test_datasets(
            [], "969,30,1", (12, 4, 0), seq_length=64, seed=3,
            train_data_prefix=[instruct_corpus],
            valid_data_prefix=[instruct_corpus])
        assert len(train) == 12 and len(valid) == 4 and test is None


class TestPreprocessTools:
    def test_preprocess_data_cli(self, tmp_path):
        jsonl = tmp_path / "corpus.jsonl"
        docs = [" ".join(str(x) for x in np.random.RandomState(i).randint(1, 400, 20))
                for i in range(5)]
        jsonl.write_text("".join(json.dumps({"text": d}) + "\n" for d in docs))
        out_prefix = str(tmp_path / "corpus")
        subprocess.run(
            [sys.executable, str(REPO / "tools" / "preprocess_data.py"),
             "--input", str(jsonl), "--output_prefix", out_prefix,
             "--tokenizer_type", "NullTokenizer", "--append_eod",
             "--workers", "1"],
            check=True, cwd=REPO, capture_output=True)
        from megatron_llm_tpu.data.indexed_dataset import MMapIndexedDataset
        ds = MMapIndexedDataset(out_prefix)
        assert len(ds) == 5
        first = np.asarray(ds[0])
        expect = [int(t) for t in docs[0].split()] + [0]  # eod == 0
        np.testing.assert_array_equal(first, expect)

    def test_pack_docs(self):
        sys.path.insert(0, str(REPO / "tools"))
        try:
            from preprocess_instruct_data import pack_docs
        finally:
            sys.path.pop(0)
        docs = [(10, list(range(30)), [int(Role.user)] * 30),
                (10, list(range(20)), [int(Role.assistant)] * 20),
                (10, list(range(8)), [int(Role.user)] * 8)]
        packed = pack_docs(docs, sep_token=1, max_seq_length=32)
        # doc0 (30) alone; doc1 (20) + sep + doc2 (8) = 29 fit together
        assert len(packed) == 2
        _, tokens, roles = packed[1]
        assert len(tokens) == len(roles) == 29
        assert roles[20] == int(Role.PACK_SEP)
        # oversize doc truncates
        packed = pack_docs([(5, list(range(50)), [0] * 50)], 1, 32)
        assert len(packed[0][1]) == 32


def test_instruction_training_end_to_end(instruct_corpus, tmp_path):
    """Tiny instruction-tuning run through pretrain() with --data_type
    instruction; loss must be finite and only assistant tokens drive it."""
    from megatron_llm_tpu.config import Config, apply_architecture
    from megatron_llm_tpu.training import pretrain

    cfg = Config()
    apply_architecture(cfg, "llama2")
    cfg.model.num_layers = 2
    cfg.model.hidden_size = 64
    cfg.model.num_attention_heads = 4
    cfg.model.num_attention_heads_kv = 2
    cfg.model.vocab_size = 512
    cfg.model.max_position_embeddings = 64
    cfg.data.seq_length = 32
    cfg.data.data_path = [instruct_corpus]
    cfg.data.data_type = "instruction"
    cfg.data.tokenizer_type = "NullTokenizer"
    cfg.training.params_dtype = "float32"
    cfg.training.use_flash_attn = False
    cfg.training.micro_batch_size = 2
    cfg.training.global_batch_size = 2
    cfg.training.train_iters = 4
    cfg.training.eval_iters = 1
    cfg.training.eval_interval = 100
    cfg.optimizer.lr = 1e-3
    cfg.optimizer.lr_warmup_iters = 1
    cfg.logging.log_interval = 2
    cfg.finalize(n_devices=1)
    result = pretrain(cfg)
    assert result["iteration"] == 4
    assert np.isfinite(float(result["last_metrics"]["lm loss"]))
