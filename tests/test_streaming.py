"""Streaming serving tier tests (serving/streaming/, ISSUE 18).

Four layers, mirroring the subsystem: the bounded per-request emission
queue and SSE wire helpers in isolation, the engine's submit_stream
path against the buffered path (token identity), a real 2-replica
loopback fleet streaming end-to-end through the router (byte/token
identity, trace propagation, mid-stream death semantics), and the
elastic-discovery + admission-queue control plane.
"""

import http.client
import json
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlparse

import pytest

from megatron_llm_tpu.serving.router.admission import (
    AdmissionOverflow,
    AdmissionQueue,
)
from megatron_llm_tpu.serving.streaming import (
    StreamEvent,
    StreamQueue,
    parse_sse,
    sse_encode,
    sse_scan_terminal,
)

# ---------------------------------------------------------------------------
# StreamQueue: bounded emission with honest drop-to-terminal semantics
# ---------------------------------------------------------------------------


def test_stream_queue_orders_tokens_then_terminal_exactly_once():
    q = StreamQueue(maxsize=8)
    assert q.publish_tokens([1, 2], [0.1, 0.2]) == 0
    assert q.publish_tokens([3], [0.3]) == 0
    q.publish_terminal(StreamEvent("done", data={"outcome": "ok"}))
    evs = list(q.iter_events(timeout=1.0))
    assert [e.kind for e in evs] == ["token", "token", "done"]
    assert evs[0].tokens == [1, 2] and evs[1].tokens == [3]
    # terminal is delivered exactly once; afterwards the queue is dry
    assert q.next_event(timeout=0.01) is None


def test_stream_queue_overflow_drops_with_honest_count():
    q = StreamQueue(maxsize=2)
    shed = sum(q.publish_tokens([i]) for i in range(5))
    assert shed == 3 and q.dropped == 3, "publish must never block"
    q.publish_terminal(StreamEvent("done", data={}))
    evs = list(q.iter_events(timeout=1.0))
    # the queued incrementals survive, the terminal carries the count
    assert [e.kind for e in evs] == ["token", "token", "done"]
    assert evs[-1].data["dropped_events"] == 3


def test_stream_queue_abandon_sheds_future_publishes():
    q = StreamQueue(maxsize=8)
    q.publish_tokens([1])
    q.abandon()
    assert q.publish_tokens([2]) == 1, "post-abandon publishes are shed"
    q.publish_terminal(StreamEvent("done", data={}))
    assert q.next_event(timeout=0.01) is None, "abandoned consumers get nothing"


def test_stream_queue_first_terminal_wins():
    q = StreamQueue(maxsize=8)
    q.publish_terminal(StreamEvent("error", data={"error": "boom"}))
    q.publish_terminal(StreamEvent("done", data={}))
    evs = list(q.iter_events(timeout=1.0))
    assert [e.kind for e in evs] == ["error"]


# ---------------------------------------------------------------------------
# SSE wire helpers
# ---------------------------------------------------------------------------


def test_sse_encode_parse_roundtrip():
    raw = (sse_encode("token", {"tokens": [1, 2]})
           + sse_encode("done", {"text": ["hi\nthere"]}))
    events = parse_sse(raw)
    assert [e for e, _ in events] == ["token", "done"]
    assert events[0][1]["tokens"] == [1, 2]
    assert events[1][1]["text"] == ["hi\nthere"]


def test_sse_terminal_scan_across_chunk_boundaries():
    raw = sse_encode("token", {"t": 1}) + sse_encode("done", {"ok": 1})
    for cut in range(1, len(raw)):
        tail, seen = b"\n", False
        for chunk in (raw[:cut], raw[cut:]):
            seen, tail = sse_scan_terminal(tail, chunk)
            if seen:
                break
        assert seen, f"terminal frame missed when split at byte {cut}"
    # a stream with no terminal frame must never scan as terminated
    seen, _ = sse_scan_terminal(b"\n", sse_encode("token", {"t": 1}))
    assert not seen


# ---------------------------------------------------------------------------
# AdmissionQueue
# ---------------------------------------------------------------------------


def test_admission_fifo_grant_and_overflow():
    adm = AdmissionQueue(limit=1, depth=2, timeout_s=5.0)
    assert adm.try_admit() == 0.0  # fast path
    order = []

    def waiter(tag):
        if adm.try_admit() is not None:
            order.append(tag)

    t1 = threading.Thread(target=waiter, args=("first",))
    t1.start()
    while adm.queued() < 1:
        time.sleep(0.005)
    t2 = threading.Thread(target=waiter, args=("second",))
    t2.start()
    while adm.queued() < 2:
        time.sleep(0.005)
    with pytest.raises(AdmissionOverflow):
        adm.try_admit()  # bounded queue full -> immediate 503 material
    adm.release()
    t1.join(timeout=5)
    adm.release()
    t2.join(timeout=5)
    assert order == ["first", "second"], "grants must be strict FIFO"
    adm.release()
    assert adm.stats()["inflight"] == 0 and adm.stats()["overflows"] == 1


def test_admission_deadline_timeout_returns_none():
    adm = AdmissionQueue(limit=1, depth=4, timeout_s=5.0)
    assert adm.try_admit() == 0.0
    t0 = time.monotonic()
    assert adm.try_admit(deadline_s=0.05) is None, "saturated past deadline"
    assert time.monotonic() - t0 < 2.0
    assert adm.stats()["timeouts"] == 1 and adm.queued() == 0
    adm.release()
    assert adm.try_admit() == 0.0, "timed-out waiter must not leak a slot"


# ---------------------------------------------------------------------------
# Real-engine fleet (module scope: weights shared across tests)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet():
    """Two continuous-batching replicas with identical weights behind real
    MegatronServers, mirroring tests/test_router.py's fixture."""
    import jax

    from megatron_llm_tpu.generation import ContinuousBatchingEngine
    from megatron_llm_tpu.generation.server import MegatronServer
    from megatron_llm_tpu.models import init_model_params, make_config
    from tests.test_generation import VOCAB, ToyTokenizer

    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, ffn_hidden_size=128, seq_length=128,
        max_position_embeddings=256, vocab_size=VOCAB,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype="float32", use_flash_attn=False,
    )
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    servers, urls = [], []
    for _ in range(2):
        engine = ContinuousBatchingEngine(cfg, params, ToyTokenizer(),
                                          max_slots=4, max_seq=128)
        srv = MegatronServer(engine)
        port = srv.start_background(port=0)
        servers.append(srv)
        urls.append(f"http://127.0.0.1:{port}")
    yield servers, urls
    for srv in servers:
        try:
            srv.stop()
        except Exception:
            pass


def _put(url, payload, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="PUT")
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _stream_put(base, payload, headers=None, timeout=120):
    """PUT with incremental SSE reads; returns (status, headers, frames,
    first_frame_latency_s) where frames is parse_sse's [(event, data)]."""
    u = urlparse(base)
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=timeout)
    t0 = time.monotonic()
    conn.request("PUT", "/api", body=json.dumps(payload).encode(),
                 headers={"Content-Type": "application/json",
                          **(headers or {})})
    resp = conn.getresponse()
    hdrs = dict(resp.getheaders())
    raw, t_first = b"", None
    while True:
        chunk = resp.read1(65536)
        if not chunk:
            break
        if t_first is None:
            t_first = time.monotonic() - t0
        raw += chunk
    conn.close()
    if resp.status != 200 or not hdrs.get(
            "Content-Type", "").startswith("text/event-stream"):
        return resp.status, hdrs, json.loads(raw), t_first
    return resp.status, hdrs, parse_sse(raw), t_first


GEN = dict(tokens_to_generate=12, top_k=1, logprobs=True, random_seed=7)


def test_engine_stream_tokens_match_buffered_submit(fleet):
    """submit_stream's incremental emissions concatenate to exactly the
    tokens the non-streamed path generates — transport, not sampling."""
    servers, _ = fleet
    eng = servers[0].engine
    prompt = [3, 4, 5, 6, 7]
    kw = dict(top_k=1, termination_id=10 ** 9, return_log_probs=True)
    ref = eng.submit(prompt, 10, **kw)
    eng.run_until_idle()
    ref_toks, ref_lp = ref.result(timeout=10)
    req, q = eng.submit_stream(prompt, 10, **kw)
    eng.run_until_idle()
    toks, lps = [], []
    for ev in q.iter_events(timeout=10.0):
        if ev.kind == "token":
            toks += ev.tokens
            lps += ev.log_probs
        else:
            assert ev.kind == "done"
            assert ev.data["timing"]["tokens"] == len(toks)
            assert ev.data["timing"]["ttft_s"] is not None
    assert toks == list(ref_toks)[len(prompt):]
    assert lps == pytest.approx(list(ref_lp))


def test_replica_sse_done_body_identical_to_buffered(fleet):
    """The acceptance bar, replica-direct: a "stream": true request's
    terminal done frame carries the byte-identical generation payload of
    the buffered request, headers (trace id + TTFT stamp) precede the
    body, and the incremental frames actually stream tokens."""
    _, urls = fleet
    payload = {"prompts": ["stream me please"], **GEN}
    code, buffered = _put(urls[0] + "/api", payload)
    assert code == 200
    tid = "stream-identity-test"
    code, hdrs, frames, _ = _stream_put(
        urls[0], {**payload, "stream": True},
        headers={"X-MLT-Trace-Id": tid})
    assert code == 200
    assert hdrs["X-MLT-Trace-Id"] == tid
    assert float(hdrs["X-MLT-TTFT-S"]) > 0.0
    kinds = [e for e, _ in frames]
    assert kinds[-1] == "done" and kinds.count("done") == 1
    token_frames = [d for e, d in frames if e == "token"]
    assert token_frames, "no incremental token frames streamed"
    assert all(d["tokens"] for d in token_frames)
    done = frames[-1][1]
    # timing is per-serve metadata (ISSUE 12); the generation is not
    assert done.pop("timing", None) is not None
    buffered.pop("timing", None)
    assert done == buffered, "streaming changed the tokens"


def test_replica_health_advertises_streaming_and_registered(fleet):
    _, urls = fleet
    with urllib.request.urlopen(urls[0] + "/health", timeout=10) as resp:
        info = json.loads(resp.read())
    assert info["streaming"] is True
    assert info["registered"] is False  # no --register_url on this fixture


def test_stream_validation_rejects_unstreamable_requests(fleet):
    _, urls = fleet
    bad = [
        ({"prompts": ["a", "b"], "tokens_to_generate": 4, "stream": True},
         "exactly one prompt"),
        ({"prompts": ["a"], "tokens_to_generate": 4, "beam_width": 2,
          "stream": True}, "beam"),
        ({"prompts": ["a"], "tokens_to_generate": 0, "stream": True},
         "tokens_to_generate"),
        ({"prompts": ["a"], "tokens_to_generate": 4, "stream": "yes"},
         "boolean"),
    ]
    for payload, needle in bad:
        code, body = _put(urls[0] + "/api", payload)
        assert code == 400 and needle in body["error"], (payload, body)


def test_router_stream_passthrough_identical_and_traced(fleet):
    """Streamed through the router == streamed direct == buffered, with
    one trace id spanning both tiers (echoed header + replica timing)."""
    from megatron_llm_tpu.serving.router.server import RouterServer

    _, urls = fleet
    router = RouterServer(urls, policy="round_robin", poll_interval=30.0)
    try:
        port = router.start_background()
        base = f"http://127.0.0.1:{port}"
        payload = {"prompts": ["route the stream"], **GEN, "stream": True}
        tid = "router-stream-trace"
        code, hdrs, frames, _ = _stream_put(
            base, payload, headers={"X-MLT-Trace-Id": tid})
        assert code == 200
        assert hdrs["X-MLT-Trace-Id"] == tid
        assert float(hdrs["X-MLT-TTFT-S"]) > 0.0
        assert [e for e, _ in frames][-1] == "done"
        routed_done = frames[-1][1]
        # the replica resolved the SAME trace id (its flight record
        # produced the timing block under that id)
        assert routed_done.pop("timing", None) is not None
        for u in urls:
            code, _, direct, _ = _stream_put(u, payload)
            assert code == 200
            d = direct[-1][1]
            d.pop("timing", None)
            assert routed_done == d, "routing changed the streamed tokens"
        code, buffered = _put(base + "/api",
                              {k: v for k, v in payload.items()
                               if k != "stream"})
        assert code == 200
        buffered.pop("timing", None)
        assert routed_done == buffered
    finally:
        router.stop()


# ---------------------------------------------------------------------------
# Mid-stream death + admission + discovery (programmable fake replicas)
# ---------------------------------------------------------------------------


class _FakeStreamReplica:
    """Minimal /api + /health replica; ``mode`` picks the PUT behavior:
    'ok' buffered JSON, 'sse' a well-terminated stream, 'die_mid_stream'
    two token frames then FIN with no terminal frame, 'slow_ok' buffered
    after ``delay`` (capacity 1 — concurrent requests get 503)."""

    def __init__(self, mode="ok", delay=0.2):
        self.mode = mode
        self.delay = delay
        self.requests = 0
        self.health_polls = 0
        self._busy = threading.Semaphore(1)
        outer = self

        class H(BaseHTTPRequestHandler):
            def do_PUT(self):
                outer.requests += 1
                if outer.mode in ("sse", "die_mid_stream"):
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Connection", "close")
                    self.send_header("X-MLT-TTFT-S", "0.001")
                    self.end_headers()
                    self.wfile.write(sse_encode("token", {"tokens": [1]}))
                    self.wfile.write(sse_encode("token", {"tokens": [2]}))
                    self.wfile.flush()
                    if outer.mode == "sse":
                        self.wfile.write(sse_encode(
                            "done", {"text": ["ok"], "served_by": outer.url}))
                        self.wfile.flush()
                    else:
                        self.connection.shutdown(socket.SHUT_WR)
                    return
                if outer.mode == "slow_ok":
                    if not outer._busy.acquire(blocking=False):
                        body = json.dumps({"error": "queue full",
                                           "retry_after": 0.05}).encode()
                        self.send_response(503)
                        self.send_header("Retry-After", "1")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    try:
                        time.sleep(outer.delay)
                    finally:
                        outer._busy.release()
                body = json.dumps({"text": ["ok"],
                                   "served_by": outer.url}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                outer.health_polls += 1
                body = json.dumps({
                    "status": "ok", "replica_id": outer.url,
                    "seq": outer.health_polls, "uptime_s": 1.0,
                    "active_slots": 0, "max_slots": 1, "queued": 0,
                    "streaming": True, "registered": True,
                }).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_router_midstream_death_is_structured_never_silent():
    """Once the first body byte is forwarded the request is committed:
    a replica dying mid-stream yields a terminal SSE error frame (the
    client can tell completion from truncation), feeds the breaker, and
    is never retried — the healthy twin sees zero requests."""
    from megatron_llm_tpu.serving.router import SUSPECT
    from megatron_llm_tpu.serving.router.server import RouterServer

    dying = _FakeStreamReplica(mode="die_mid_stream")
    healthy = _FakeStreamReplica(mode="sse")
    router = RouterServer([dying.url, healthy.url], policy="round_robin",
                          poll_interval=30.0)
    try:
        port = router.start_background()
        payload = {"prompts": ["x"], "tokens_to_generate": 4,
                   "stream": True}
        for _ in range(2):  # round_robin: one request lands on each
            code, _, frames, _ = _stream_put(
                f"http://127.0.0.1:{port}", payload)
            assert code == 200
            kinds = [e for e, _ in frames]
            assert kinds[-1] in ("done", "error"), (
                f"silent truncation: stream ended with {kinds}")
            if kinds[-1] == "error":
                data = frames[-1][1]
                assert data["truncated"] is True
                assert data["replica"] == dying.url
                assert "not retried" in data["error"]
        assert dying.requests == 1 and healthy.requests == 1, (
            "a mid-stream death must never be retried")
        assert router.registry.get(dying.url).state == SUSPECT
        assert router.registry.get(healthy.url).state != SUSPECT
    finally:
        router.stop()
        dying.stop()
        healthy.stop()


def test_router_admission_queue_absorbs_burst():
    """A saturation burst against a capacity-1 replica: without the
    admission queue (and no proxy retries) some requests eat 503s; with
    it, arrivals wait their turn and 0 requests are dropped."""
    from megatron_llm_tpu.serving.router.server import RouterServer

    def burst(port, n=6):
        codes = [None] * n

        def worker(i):
            codes[i] = _put(f"http://127.0.0.1:{port}/api",
                            {"prompts": ["b"], "tokens_to_generate": 1})[0]

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return codes

    rep = _FakeStreamReplica(mode="slow_ok", delay=0.15)
    baseline = RouterServer([rep.url], poll_interval=30.0, max_retries=0)
    try:
        port = baseline.start_background()
        codes = burst(port)
        assert 503 in codes, "burst too small to saturate the baseline"
    finally:
        baseline.stop()
        rep.stop()

    rep = _FakeStreamReplica(mode="slow_ok", delay=0.15)
    gated = RouterServer([rep.url], poll_interval=30.0, max_retries=0,
                         admission_depth=16, admission_limit=1,
                         admission_timeout_s=30.0)
    try:
        port = gated.start_background()
        codes = burst(port)
        assert codes == [200] * len(codes), (
            f"admission queue dropped requests: {codes}")
        # the handler releases AFTER writing the body — give the last
        # server thread a beat to reach its finally block
        deadline = time.monotonic() + 5
        while (gated.admission.stats()["inflight"]
               and time.monotonic() < deadline):
            time.sleep(0.01)
        stats = gated.admission.stats()
        assert stats["overflows"] == 0 and stats["inflight"] == 0
    finally:
        gated.stop()
        rep.stop()


def test_elastic_registration_lifecycle():
    """A router started with zero static replicas admits a registering
    replica (immediately routable), expires it through the breaker when
    it dies, and re-admits its restart on a new port."""
    from megatron_llm_tpu.serving.router import EJECTED, HEALTHY
    from megatron_llm_tpu.serving.router.server import RouterServer

    router = RouterServer([], allow_registration=True, poll_interval=30.0,
                          eject_after=2)
    rep = _FakeStreamReplica(mode="ok")
    try:
        port = router.start_background()
        base = f"http://127.0.0.1:{port}"
        code, body = _put(base + "/api",
                          {"prompts": ["x"], "tokens_to_generate": 1})
        assert code == 503, "an empty elastic fleet sheds, it can't route"

        def register(url):
            req = urllib.request.Request(
                base + "/admin/register",
                data=json.dumps({"replica": url}).encode(), method="POST")
            with urllib.request.urlopen(req, timeout=10) as resp:
                return json.loads(resp.read())

        ack = register(rep.url)
        assert ack["added"] is True and ack["state"] == HEALTHY
        # registration polled synchronously: routable with no extra wait
        code, body = _put(base + "/api",
                          {"prompts": ["x"], "tokens_to_generate": 1})
        assert code == 200 and body["served_by"] == rep.url
        assert register(rep.url)["added"] is False  # heartbeat no-op
        # /health marks the replica as discovered, not statically configured
        with urllib.request.urlopen(base + "/health", timeout=10) as resp:
            rows = json.loads(resp.read())["replicas"]
        assert [r["registered"] for r in rows] == [True]

        # kill it; drive the breaker the way the poll loop would
        dead_url = rep.url
        rep.stop()
        replica = router.registry.get(dead_url)
        for _ in range(2):
            router.poller.poll_once(replica)
        assert replica.state == EJECTED

        # restart on a new port: a fresh registration re-enters the fleet
        rep = _FakeStreamReplica(mode="ok")
        assert register(rep.url)["added"] is True
        code, body = _put(base + "/api",
                          {"prompts": ["x"], "tokens_to_generate": 1})
        assert code == 200 and body["served_by"] == rep.url
    finally:
        router.stop()
        rep.stop()


def test_register_endpoint_403_when_registration_disabled():
    from megatron_llm_tpu.serving.router.server import RouterServer

    rep = _FakeStreamReplica(mode="ok")
    router = RouterServer([rep.url], poll_interval=30.0)
    try:
        port = router.start_background()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/admin/register",
            data=json.dumps({"replica": "http://127.0.0.1:1"}).encode(),
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 403
    finally:
        router.stop()
        rep.stop()


def test_run_router_allows_empty_fleet_only_with_registration(monkeypatch):
    """tools/run_router.py: --allow_registration lifts the static-replica
    requirement (argparse-level contract, no sockets)."""
    import tools.run_router as rr
    from megatron_llm_tpu.serving.router.server import RouterServer

    with pytest.raises(SystemExit):
        rr.main(["--policy", "least_loaded"])  # still required without it

    seen = {}

    def fake_bind(self, host, port):
        seen["registration"] = self.allow_registration
        seen["admission"] = self.admission
        return 0

    monkeypatch.setattr(RouterServer, "bind", fake_bind)
    monkeypatch.setattr(RouterServer, "serve", lambda self: None)
    rr.main(["--allow_registration", "--admission_queue_depth", "8",
             "--port", "0"])
    assert seen["registration"] is True
    assert seen["admission"] is not None and seen["admission"].depth == 8
