"""Flash-in-ring context parallelism (parallel/ring.py round-5 addition).

The Pallas kernel computes each (Q-chunk, KV-chunk) ring step for the
contiguous layout; chunk results merge by log-sum-exp and the backward
calls the flash bwd kernel per chunk against the GLOBAL (out, lse)
residuals, dk/dv accumulators riding the ppermute ring home. These tests
run the composition in interpret mode on the virtual CPU mesh and pin it
against full (unsharded) XLA attention — forward and gradients, causal /
bidirectional / GQA / segment-gated.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from megatron_llm_tpu.core import parallel_state as ps
from megatron_llm_tpu.parallel import compat
from megatron_llm_tpu.ops.attention import make_attention_bias, xla_attention
from megatron_llm_tpu.parallel.ring import _ring_attention_flash


def _qkv(key, b=2, s=256, n=4, nkv=2, d=64):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, n, d), jnp.float32) * 0.3
    k = jax.random.normal(kk, (b, s, nkv, d), jnp.float32) * 0.3
    v = jax.random.normal(kv, (b, s, nkv, d), jnp.float32) * 0.3
    return q, k, v


def _run_ring_flash(mesh, cp, q, k, v, seg=None, causal=True):
    scale = 1.0 / (q.shape[-1] ** 0.5)
    qs = P(None, "cp", None, None)
    segs = P(None, "cp")

    if seg is None:
        fn = compat.shard_map(
            lambda q_, k_, v_: _ring_attention_flash(
                q_, k_, v_, None, None, axis_name=ps.CP_AXIS, scale=scale,
                causal=causal, interpret=True),
            mesh=mesh, in_specs=(qs, qs, qs), out_specs=qs,
            axis_names={ps.CP_AXIS}, check_vma=False)

        def loss(q_, k_, v_):
            o = fn(q_, k_, v_)
            return (o.astype(jnp.float32) ** 2).sum(), o

        return jax.jit(jax.value_and_grad(
            loss, argnums=(0, 1, 2), has_aux=True))(q, k, v)

    fn = compat.shard_map(
        lambda q_, k_, v_, s_: _ring_attention_flash(
            q_, k_, v_, s_, s_, axis_name=ps.CP_AXIS, scale=scale,
            causal=causal, interpret=True),
        mesh=mesh, in_specs=(qs, qs, qs, segs), out_specs=qs,
        axis_names={ps.CP_AXIS}, check_vma=False)

    def loss(q_, k_, v_):
        o = fn(q_, k_, v_, seg)
        return (o.astype(jnp.float32) ** 2).sum(), o

    return jax.jit(jax.value_and_grad(
        loss, argnums=(0, 1, 2), has_aux=True))(q, k, v)


def _reference(q, k, v, seg=None, causal=True):
    bias = make_attention_bias(
        q.shape[1], k.shape[1], causal=causal,
        segment_ids_q=seg, segment_ids_kv=seg)

    def loss(q_, k_, v_):
        o = xla_attention(q_, k_, v_, bias=bias,
                          scale=1.0 / (q.shape[-1] ** 0.5))
        return (o.astype(jnp.float32) ** 2).sum(), o

    return jax.value_and_grad(loss, argnums=(0, 1, 2), has_aux=True)(q, k, v)


@pytest.mark.parametrize("cp,causal", [(2, True), (2, False), (4, True)])
def test_ring_flash_parity(eight_devices, cp, causal):
    mesh = ps.build_mesh(context_parallel_size=cp, devices=eight_devices[:cp])
    q, k, v = _qkv(jax.random.PRNGKey(0), s=128 * cp)
    with ps.global_mesh(mesh), mesh:
        (val, out), grads = _run_ring_flash(mesh, cp, q, k, v, causal=causal)
    (rval, rout), rgrads = _reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(rout),
                               atol=2e-5, rtol=2e-5)
    for g, rg in zip(grads, rgrads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                   atol=3e-4, rtol=3e-4)


def test_ring_flash_bf16_accumulation(eight_devices):
    """bf16 inputs: per-chunk partials stay fp32 through the cross-chunk
    merge (one final rounding, like the jnp ring) — the output must track
    an fp32-computed reference to bf16 resolution, independent of cp."""
    cp = 4
    mesh = ps.build_mesh(context_parallel_size=cp, devices=eight_devices[:cp])
    q, k, v = _qkv(jax.random.PRNGKey(3), s=128 * cp)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    with ps.global_mesh(mesh), mesh:
        (_, out), _grads = _run_ring_flash(mesh, cp, qb, kb, vb, causal=True)
    assert out.dtype == jnp.bfloat16
    (_, rout), _ = _reference(qb.astype(jnp.float32), kb.astype(jnp.float32),
                              vb.astype(jnp.float32), causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(rout), atol=2e-2, rtol=2e-2)


def test_ring_flash_segments(eight_devices):
    """Packed-document gating across chunk boundaries: a document spanning
    the cp split must not attend across its boundary."""
    cp = 2
    mesh = ps.build_mesh(context_parallel_size=cp, devices=eight_devices[:cp])
    q, k, v = _qkv(jax.random.PRNGKey(1), b=2, s=256)
    # doc boundary NOT on the chunk boundary (doc 0: [0,180), doc 1: rest)
    seg = (jnp.arange(256)[None, :] >= 180).astype(jnp.int32)
    seg = jnp.broadcast_to(seg, (2, 256))
    with ps.global_mesh(mesh), mesh:
        (val, out), grads = _run_ring_flash(mesh, cp, q, k, v, seg=seg)
    (rval, rout), rgrads = _reference(q, k, v, seg=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(rout),
                               atol=2e-5, rtol=2e-5)
    for g, rg in zip(grads, rgrads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                   atol=3e-4, rtol=3e-4)


@pytest.mark.parametrize("cp,segmented", [(2, False), (4, False), (2, True)])
def test_ring_flash_striped_zigzag(eight_devices, cp, segmented):
    """Striped (zigzag) flash ring vs full attention in ORIGINAL token
    order: apply the standard zigzag permutation to the inputs, run the
    striped kernels, and the output/grad rows must equal the reference's
    under the same permutation. Covers the 3-live-pairs case analysis
    (AA switch, BA always-full, BB swapped-roles switch, AB masked)."""
    from megatron_llm_tpu.parallel.ring import (
        _ring_attention_flash,
        zigzag_permutation,
    )

    s = 256 * cp  # each half-chunk is 128 — the kernel tile minimum
    mesh = ps.build_mesh(context_parallel_size=cp, devices=eight_devices[:cp])
    q, k, v = _qkv(jax.random.PRNGKey(4), b=2, s=s)
    seg = None
    if segmented:
        seg = (jnp.arange(s)[None, :] >= (s // 2 + 64)).astype(jnp.int32)
        seg = jnp.broadcast_to(seg, (2, s))
    perm = zigzag_permutation(s, cp)
    qp, kp, vp = q[:, perm], k[:, perm], v[:, perm]
    segp = seg[:, perm] if seg is not None else None

    scale = 1.0 / (q.shape[-1] ** 0.5)
    qs = P(None, "cp", None, None)
    segs = P(None, "cp")

    with ps.global_mesh(mesh), mesh:
        if segp is None:
            fn = compat.shard_map(
                lambda q_, k_, v_: _ring_attention_flash(
                    q_, k_, v_, None, None, axis_name=ps.CP_AXIS,
                    scale=scale, causal=True, interpret=True, striped=True),
                mesh=mesh, in_specs=(qs, qs, qs), out_specs=qs,
                axis_names={ps.CP_AXIS}, check_vma=False)

            def loss(q_, k_, v_):
                o = fn(q_, k_, v_)
                return (o.astype(jnp.float32) ** 2).sum(), o
        else:
            fn = compat.shard_map(
                lambda q_, k_, v_, s_: _ring_attention_flash(
                    q_, k_, v_, s_, s_, axis_name=ps.CP_AXIS,
                    scale=scale, causal=True, interpret=True, striped=True),
                mesh=mesh, in_specs=(qs, qs, qs, segs), out_specs=qs,
                axis_names={ps.CP_AXIS}, check_vma=False)

            def loss(q_, k_, v_):
                o = fn(q_, k_, v_, segp)
                return (o.astype(jnp.float32) ** 2).sum(), o

        (val, out), grads = jax.jit(jax.value_and_grad(
            loss, argnums=(0, 1, 2), has_aux=True))(qp, kp, vp)

    (rval, rout), rgrads = _reference(q, k, v, seg=seg, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(rout[:, perm]),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(float(val), float(rval), rtol=1e-5)
    for g, rg in zip(grads, rgrads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg[:, perm]),
                                   atol=3e-4, rtol=3e-4)


def test_ring_flash_dispatch_routing(monkeypatch):
    """Drive _dispatch_local itself (the production routing table), with
    the three backends stubbed to recorders: every branch — contiguous
    flash, non-causal-permuted flash, striped zigzag, and each jnp
    fallback reason (sliding window, undeclared zigzag, off-tile shapes,
    non-TPU target) — must pick exactly the path the docstring promises."""
    from megatron_llm_tpu.parallel import ring

    calls = []

    def fake_flash(q, k, v, sq, skv, *, axis_name, scale, causal,
                   interpret, striped=False):
        calls.append(("flash", causal, striped))
        return q

    def fake_local(q, k, v, qi, ki, sq, skv, **kw):
        calls.append(("jnp", kw["causal"], False))
        return q

    monkeypatch.setattr(ring, "_ring_attention_flash", fake_flash)
    monkeypatch.setattr(ring, "_ring_attention_local", fake_local)
    monkeypatch.setattr(ring, "_local_indices",
                        lambda tok, s, ax: jnp.arange(s))
    monkeypatch.setattr(ring.ps, "target_platform", lambda: "tpu")

    q = jnp.zeros((1, 256, 4, 64))
    kw = dict(axis_name="cp", scale=0.125, sliding_window=None)
    tok = jnp.arange(256)

    def route(**over):
        calls.clear()
        args = dict(kw, causal=True, zigzag=False)
        args.update(over)
        ring._dispatch_local(args.pop("q", q), q, q, None,
                             args.pop("tok", None), **args)
        return calls[-1]

    assert route() == ("flash", True, False)  # contiguous
    assert route(tok=tok, zigzag=True) == ("flash", True, True)  # striped
    assert route(tok=tok, causal=False) == ("flash", False, False)  # order-
    # independent masking: plain flash even though permuted
    assert route(tok=tok) == ("jnp", True, False)  # undeclared permutation
    assert route(sliding_window=64) == ("jnp", True, False)
    assert route(q=jnp.zeros((1, 200, 4, 64)))[0] == "jnp"  # off-tile seq
    assert route(q=jnp.zeros((1, 256, 4, 32)))[0] == "jnp"  # head_dim 32
    # striped needs BOTH half-chunks on the kernel tile grid
    assert route(q=jnp.zeros((1, 192, 4, 64)), tok=jnp.arange(192),
                 zigzag=True)[0] == "jnp"

    monkeypatch.setattr(ring.ps, "target_platform", lambda: "cpu")
    assert route() == ("jnp", True, False)  # non-TPU target
