"""Flash-in-ring context parallelism (parallel/ring.py round-5 addition).

The Pallas kernel computes each (Q-chunk, KV-chunk) ring step for the
contiguous layout; chunk results merge by log-sum-exp and the backward
calls the flash bwd kernel per chunk against the GLOBAL (out, lse)
residuals, dk/dv accumulators riding the ppermute ring home. These tests
run the composition in interpret mode on the virtual CPU mesh and pin it
against full (unsharded) XLA attention — forward and gradients, causal /
bidirectional / GQA / segment-gated.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from megatron_llm_tpu.core import parallel_state as ps
from megatron_llm_tpu.ops.attention import make_attention_bias, xla_attention
from megatron_llm_tpu.parallel.ring import (
    _flash_ring_supported,
    _ring_attention_flash,
)


def _qkv(key, b=2, s=256, n=4, nkv=2, d=64):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, n, d), jnp.float32) * 0.3
    k = jax.random.normal(kk, (b, s, nkv, d), jnp.float32) * 0.3
    v = jax.random.normal(kv, (b, s, nkv, d), jnp.float32) * 0.3
    return q, k, v


def _run_ring_flash(mesh, cp, q, k, v, seg=None, causal=True):
    scale = 1.0 / (q.shape[-1] ** 0.5)
    qs = P(None, "cp", None, None)
    segs = P(None, "cp")

    if seg is None:
        fn = jax.shard_map(
            lambda q_, k_, v_: _ring_attention_flash(
                q_, k_, v_, None, None, axis_name=ps.CP_AXIS, scale=scale,
                causal=causal, interpret=True),
            mesh=mesh, in_specs=(qs, qs, qs), out_specs=qs,
            axis_names={ps.CP_AXIS}, check_vma=False)

        def loss(q_, k_, v_):
            o = fn(q_, k_, v_)
            return (o.astype(jnp.float32) ** 2).sum(), o

        return jax.jit(jax.value_and_grad(
            loss, argnums=(0, 1, 2), has_aux=True))(q, k, v)

    fn = jax.shard_map(
        lambda q_, k_, v_, s_: _ring_attention_flash(
            q_, k_, v_, s_, s_, axis_name=ps.CP_AXIS, scale=scale,
            causal=causal, interpret=True),
        mesh=mesh, in_specs=(qs, qs, qs, segs), out_specs=qs,
        axis_names={ps.CP_AXIS}, check_vma=False)

    def loss(q_, k_, v_):
        o = fn(q_, k_, v_, seg)
        return (o.astype(jnp.float32) ** 2).sum(), o

    return jax.jit(jax.value_and_grad(
        loss, argnums=(0, 1, 2), has_aux=True))(q, k, v)


def _reference(q, k, v, seg=None, causal=True):
    bias = make_attention_bias(
        q.shape[1], k.shape[1], causal=causal,
        segment_ids_q=seg, segment_ids_kv=seg)

    def loss(q_, k_, v_):
        o = xla_attention(q_, k_, v_, bias=bias,
                          scale=1.0 / (q.shape[-1] ** 0.5))
        return (o.astype(jnp.float32) ** 2).sum(), o

    return jax.value_and_grad(loss, argnums=(0, 1, 2), has_aux=True)(q, k, v)


@pytest.mark.parametrize("cp,causal", [(2, True), (2, False), (4, True)])
def test_ring_flash_parity(eight_devices, cp, causal):
    mesh = ps.build_mesh(context_parallel_size=cp, devices=eight_devices[:cp])
    q, k, v = _qkv(jax.random.PRNGKey(0), s=128 * cp)
    with ps.global_mesh(mesh), mesh:
        (val, out), grads = _run_ring_flash(mesh, cp, q, k, v, causal=causal)
    (rval, rout), rgrads = _reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(rout),
                               atol=2e-5, rtol=2e-5)
    for g, rg in zip(grads, rgrads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                   atol=3e-4, rtol=3e-4)


def test_ring_flash_bf16_accumulation(eight_devices):
    """bf16 inputs: per-chunk partials stay fp32 through the cross-chunk
    merge (one final rounding, like the jnp ring) — the output must track
    an fp32-computed reference to bf16 resolution, independent of cp."""
    cp = 4
    mesh = ps.build_mesh(context_parallel_size=cp, devices=eight_devices[:cp])
    q, k, v = _qkv(jax.random.PRNGKey(3), s=128 * cp)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    with ps.global_mesh(mesh), mesh:
        (_, out), _grads = _run_ring_flash(mesh, cp, qb, kb, vb, causal=True)
    assert out.dtype == jnp.bfloat16
    (_, rout), _ = _reference(qb.astype(jnp.float32), kb.astype(jnp.float32),
                              vb.astype(jnp.float32), causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(rout), atol=2e-2, rtol=2e-2)


def test_ring_flash_segments(eight_devices):
    """Packed-document gating across chunk boundaries: a document spanning
    the cp split must not attend across its boundary."""
    cp = 2
    mesh = ps.build_mesh(context_parallel_size=cp, devices=eight_devices[:cp])
    q, k, v = _qkv(jax.random.PRNGKey(1), b=2, s=256)
    # doc boundary NOT on the chunk boundary (doc 0: [0,180), doc 1: rest)
    seg = (jnp.arange(256)[None, :] >= 180).astype(jnp.int32)
    seg = jnp.broadcast_to(seg, (2, 256))
    with ps.global_mesh(mesh), mesh:
        (val, out), grads = _run_ring_flash(mesh, cp, q, k, v, seg=seg)
    (rval, rout), rgrads = _reference(q, k, v, seg=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(rout),
                               atol=2e-5, rtol=2e-5)
    for g, rg in zip(grads, rgrads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                   atol=3e-4, rtol=3e-4)


def test_ring_flash_gating():
    """The dispatcher must fall back to the jnp ring for the structures the
    kernel cannot mask: zigzag token_idx, sliding windows, off-tile seqs."""
    q = jnp.zeros((1, 256, 4, 64))
    assert _flash_ring_supported(q, None, None)
    assert not _flash_ring_supported(q, jnp.arange(256), None)  # zigzag
    assert not _flash_ring_supported(q, None, 128)  # sliding window
    assert not _flash_ring_supported(jnp.zeros((1, 200, 4, 64)), None, None)
    assert not _flash_ring_supported(jnp.zeros((1, 256, 4, 32)), None, None)
