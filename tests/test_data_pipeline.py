"""Data pipeline tests: .bin/.idx round-trip, GPT dataset assembly, samplers,
blending (reference analog: megatron/data/test/test_indexed_dataset.py)."""

import numpy as np
import pytest

from megatron_llm_tpu.data.batch_utils import get_ltor_batch
from megatron_llm_tpu.data.blendable_dataset import BlendableDataset, build_blending_indices
from megatron_llm_tpu.data.gpt_dataset import (
    GPTDataset,
    build_train_valid_test_datasets,
    get_train_valid_test_split_,
)
from megatron_llm_tpu.data.indexed_dataset import (
    MMapIndexedDataset,
    MMapIndexedDatasetBuilder,
    make_builder,
    make_dataset,
)
from megatron_llm_tpu.data.samplers import (
    MegatronPretrainingSampler,
    build_pretraining_data_loader,
)


@pytest.fixture
def toy_corpus(tmp_path):
    """20 documents of varying lengths, uint16 tokens."""
    prefix = str(tmp_path / "corpus")
    rng = np.random.RandomState(0)
    builder = make_builder(prefix + ".bin", vocab_size=1000)
    docs = []
    for i in range(20):
        doc = rng.randint(0, 1000, size=rng.randint(5, 50)).astype(np.int64)
        docs.append(doc)
        builder.add_doc(doc)
    builder.finalize(prefix + ".idx")
    return prefix, docs


def test_indexed_dataset_roundtrip(toy_corpus):
    prefix, docs = toy_corpus
    ds = make_dataset(prefix)
    assert len(ds) == 20
    assert ds.dtype == np.uint16
    for i, doc in enumerate(docs):
        np.testing.assert_array_equal(ds[i], doc.astype(np.uint16))
    # partial reads
    np.testing.assert_array_equal(ds.get(3, 2, 3), docs[3][2:5].astype(np.uint16))
    # doc_idx covers all documents
    assert ds.doc_idx[0] == 0 and ds.doc_idx[-1] == 20


def test_merge(toy_corpus, tmp_path):
    prefix, docs = toy_corpus
    merged = str(tmp_path / "merged")
    b = MMapIndexedDatasetBuilder(merged + ".bin", dtype=np.uint16)
    b.merge_file_(prefix)
    b.merge_file_(prefix)
    b.finalize(merged + ".idx")
    ds = make_dataset(merged)
    assert len(ds) == 40
    np.testing.assert_array_equal(ds[20], docs[0].astype(np.uint16))


def test_gpt_dataset_samples(toy_corpus):
    prefix, docs = toy_corpus
    indexed = make_dataset(prefix)
    total_tokens = int(indexed.sizes.sum())
    seq = 16
    n_samples = (total_tokens - 1) // seq
    ds = GPTDataset("train", indexed, np.arange(20), n_samples, seq, seed=5)
    assert len(ds) >= n_samples
    seen = set()
    for i in range(n_samples):
        s = ds[i]["text"]
        assert s.shape == (seq + 1,)
        assert s.dtype == np.int64
        seen.add(int(s[0]))
    # multi-epoch: ask for more samples than one epoch holds
    ds2 = GPTDataset("train", indexed, np.arange(20), n_samples * 3, seq, seed=5)
    assert len(ds2) >= n_samples * 3
    _ = ds2[len(ds2) - 1]


def test_split_parsing():
    idx = get_train_valid_test_split_("969, 30, 1", 1000)
    assert idx == [0, 969, 999, 1000]
    idx = get_train_valid_test_split_("100,0,0", 50)
    assert idx[-1] == 50


def test_build_train_valid_test(toy_corpus):
    prefix, _ = toy_corpus
    train, valid, test = build_train_valid_test_datasets(
        [prefix], "80,15,5", (10, 4, 1), seq_length=16, seed=3
    )
    assert train is not None and valid is not None
    assert train[0]["text"].shape == (17,)


def test_blending_indices_proportions():
    w = np.array([0.7, 0.2, 0.1])
    di, dsi = build_blending_indices(w, 1000)
    counts = np.bincount(di, minlength=3) / 1000
    np.testing.assert_allclose(counts, w, atol=0.01)
    # per-dataset sample indices are sequential
    for k in range(3):
        np.testing.assert_array_equal(np.asarray(dsi)[di == k],
                                      np.arange((di == k).sum()))


def test_sampler_resume():
    s1 = MegatronPretrainingSampler(100, 0, 10)
    batches = list(s1)
    assert len(batches) == 10 and batches[0] == list(range(10))
    s2 = MegatronPretrainingSampler(100, 30, 10)
    assert list(s2)[0] == list(range(30, 40))


def test_data_loader_end_to_end(toy_corpus):
    prefix, _ = toy_corpus
    indexed = make_dataset(prefix)
    total_tokens = int(indexed.sizes.sum())
    ds = GPTDataset("train", indexed, np.arange(20), (total_tokens - 1) // 16,
                    16, seed=5)
    it = build_pretraining_data_loader(ds, consumed_samples=0, global_batch_size=4)
    batch = next(it)
    assert batch["text"].shape == (4, 17)


def test_ltor_batch_eod_resets():
    tokens = np.array([[5, 1, 7, 9, 1, 3, 2, 4]])  # eod = 1
    out = get_ltor_batch(tokens, eod_token=1, reset_position_ids=True,
                         reset_attention_mask=True, eod_mask_loss=True)
    assert out["tokens"].shape == (1, 7)
    # segment ids bump after each EOD
    np.testing.assert_array_equal(out["segment_ids"][0], [0, 0, 1, 1, 1, 2, 2])
    # positions reset at the token after EOD
    np.testing.assert_array_equal(out["position_ids"][0], [0, 1, 0, 1, 2, 0, 1])
    # positions whose input token is EOD are masked (reference utils.py:160-161)
    np.testing.assert_array_equal(out["loss_mask"][0], [1, 0, 1, 1, 0, 1, 1])
