"""Scanned/fused Adam (optimizer.scanned_adam) parity with the optax chain.

The reference's optimizer math (optimizer/optimizer.py:58 + apex FusedAdam)
must be preserved by the memory-bounded TPU apply: clip_by_global_norm ->
adam -> masked weight decay -> lr schedule -> cast to param dtype, with the
fused path additionally folding in the 1/num_micro grad average and updating
params/moments in place slice-by-slice.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
import pytest

from megatron_llm_tpu.config.arguments import Config
from megatron_llm_tpu.optimizer.optimizer import (
    FusedGradientTransformation,
    get_optimizer,
    scanned_adam,
)


def _cfg(**kw):
    cfg = Config()
    cfg.optimizer.lr = 1e-3
    cfg.optimizer.weight_decay = 0.1
    cfg.optimizer.clip_grad = 1.0
    cfg.training.train_iters = 100
    for k, v in kw.items():
        setattr(cfg.optimizer, k, v)
    return cfg


def _params(key, stacked_rows=4):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "layers": {
            # 'kernel' leaf gets weight decay; mimic a layer stack
            "kernel": jax.random.normal(k1, (stacked_rows, 16, 8), jnp.float32),
            "scale": jnp.ones((stacked_rows, 8), jnp.float32),  # no wd
        },
        "head": {"kernel": jax.random.normal(k2, (8, 32), jnp.float32)},
        "bias": jax.random.normal(k3, (32,), jnp.float32),  # no wd
    }


def _grads(key, params):
    leaves, treedef = jax.tree_util.tree_flatten(params)
    keys = jax.random.split(key, len(leaves))
    return jax.tree_util.tree_unflatten(
        treedef,
        [jax.random.normal(k, leaf.shape, leaf.dtype) * 3.0  # big: clip fires
         for k, leaf in zip(keys, leaves)])


def _run(opt, params, n_steps=4, seed=0, fused=False, prescale=1.0):
    state = opt.init(params)
    for i in range(n_steps):
        g = _grads(jax.random.PRNGKey(100 + i), params)
        if prescale != 1.0:
            # fused folds the average in; chain consumes pre-averaged grads
            g_in = g if fused else jax.tree.map(lambda x: x * prescale, g)
        else:
            g_in = g
        if fused:
            params, state = opt.fused_apply(g_in, state, params,
                                            prescale=prescale)
        else:
            updates, state = opt.update(g_in, state, params)
            params = optax.apply_updates(params, updates)
    return params


@pytest.mark.parametrize("prescale", [1.0, 0.25])
def test_fused_matches_chain(prescale):
    cfg_chain = _cfg(scanned_update=False)
    cfg_fused = _cfg(scanned_update=True)
    params = _params(jax.random.PRNGKey(0))

    chain = get_optimizer(cfg_chain, params)
    fused = get_optimizer(cfg_fused, params)
    assert isinstance(fused, FusedGradientTransformation)

    p_chain = _run(chain, params, fused=False, prescale=prescale)
    p_fused = _run(fused, params, fused=True, prescale=prescale)
    diff = jax.tree.map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), p_chain, p_fused)
    assert max(jax.tree_util.tree_leaves(diff)) < 1e-5, diff


def test_update_api_matches_chain():
    """The generic optax `update` of scanned_adam (used under the fp16
    scaler) matches the chain too."""
    cfg = _cfg()
    params = _params(jax.random.PRNGKey(1))
    chain = get_optimizer(_cfg(scanned_update=False), params)
    sa = scanned_adam(cfg, params)
    p1 = _run(chain, params)
    p2 = _run(sa, params)
    diff = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))), p1, p2)
    assert max(jax.tree_util.tree_leaves(diff)) < 1e-5, diff


def test_scan_threshold_path():
    """Leaves over the scan threshold take the fori_loop path and still
    match whole-leaf math."""
    from megatron_llm_tpu.optimizer import optimizer as O

    orig = O._SCAN_UPDATE_MIN_ELEMENTS
    try:
        O._SCAN_UPDATE_MIN_ELEMENTS = 16  # force the sliced path
        cfg = _cfg()
        params = _params(jax.random.PRNGKey(2))
        fused = scanned_adam(cfg, params)
        p_sliced = _run(fused, params, fused=True)
    finally:
        O._SCAN_UPDATE_MIN_ELEMENTS = orig
    chain = get_optimizer(_cfg(scanned_update=False), params)
    p_chain = _run(chain, params)
    diff = jax.tree.map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), p_chain, p_sliced)
    assert max(jax.tree_util.tree_leaves(diff)) < 1e-5, diff


def test_bf16_params_update_dtype():
    """Updates are cast to the param storage dtype (both forms)."""
    cfg = _cfg()
    params = {"w": jnp.ones((8, 8), jnp.bfloat16)}
    sa = scanned_adam(cfg, params)
    state = sa.init(params)
    g = {"w": jnp.full((8, 8), 0.1, jnp.bfloat16)}
    updates, _ = sa.update(g, state, params)
    assert updates["w"].dtype == jnp.bfloat16
    new_p, _ = sa.fused_apply(g, state, params)
    assert new_p["w"].dtype == jnp.bfloat16
