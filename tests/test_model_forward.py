"""Single-device model numerics + smoke tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.models import (
    init_model_params,
    loss_from_batch,
    make_config,
    model_forward,
)


def tiny_config(model_name="llama2", **kw):
    defaults = dict(
        num_layers=2,
        hidden_size=64,
        num_attention_heads=4,
        num_attention_heads_kv=2,
        vocab_size=256,
        seq_length=32,
        max_position_embeddings=64,
        params_dtype="float32",
        use_flash_attn=False,
    )
    defaults.update(kw)
    return make_config(model_name, **defaults)


@pytest.mark.parametrize("model_name", ["llama2", "falcon", "mistral", "gpt"])
def test_forward_shapes(model_name):
    kw = {}
    if model_name == "mistral":
        kw["sliding_window_size"] = 4096
    cfg = tiny_config(model_name, **kw)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 256)
    logits, _ = model_forward(cfg, params, tokens)
    from megatron_llm_tpu.models import padded_vocab_size

    assert logits.shape == (2, 32, padded_vocab_size(256, cfg))
    assert np.isfinite(np.asarray(logits)).all()


def test_loss_and_grad_finite():
    cfg = tiny_config()
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0, 256)
    batch = {
        "tokens": tokens[:, :-1],
        "labels": tokens[:, 1:],
        "loss_mask": jnp.ones((2, 32)),
    }

    def loss_fn(p):
        return loss_from_batch(cfg, p, batch)[0]

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    leaves = jax.tree_util.tree_leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
    # loss should be ~ log(vocab) at init
    assert 4.0 < float(loss) < 8.0


def test_scan_matches_loop():
    cfg = tiny_config()
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 256)
    logits_scan, _ = model_forward(cfg, params, tokens)
    cfg.training.scan_layers = False
    logits_loop, _ = model_forward(cfg, params, tokens)
    np.testing.assert_allclose(
        np.asarray(logits_scan), np.asarray(logits_loop), atol=1e-5, rtol=1e-5
    )


def test_causality():
    """Changing a future token must not change past logits."""
    cfg = tiny_config()
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 256)
    logits1, _ = model_forward(cfg, params, tokens)
    tokens2 = tokens.at[0, 10].set((tokens[0, 10] + 1) % 256)
    logits2, _ = model_forward(cfg, params, tokens2)
    np.testing.assert_allclose(
        np.asarray(logits1[0, :10]), np.asarray(logits2[0, :10]), atol=1e-5
    )
    assert not np.allclose(np.asarray(logits1[0, 10:]), np.asarray(logits2[0, 10:]))


def test_sliding_window_masks_far_context():
    cfg = tiny_config("mistral", sliding_window_size=4096)
    cfg.model.sliding_window_size = 4
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 256)
    logits1, _ = model_forward(cfg, params, tokens)
    # token 0 is outside the window of position 15 (window 4) -> no effect
    tokens2 = tokens.at[0, 0].set((tokens[0, 0] + 1) % 256)
    logits2, _ = model_forward(cfg, params, tokens2)
    np.testing.assert_allclose(
        np.asarray(logits1[0, 15]), np.asarray(logits2[0, 15]), atol=1e-5
    )


def test_segment_ids_block_cross_document_attention():
    cfg = tiny_config()
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 256)
    seg = jnp.concatenate([jnp.zeros((1, 8), jnp.int32), jnp.ones((1, 8), jnp.int32)], 1)
    pos = jnp.concatenate([jnp.arange(8), jnp.arange(8)])[None]
    logits1, _ = model_forward(cfg, params, tokens, segment_ids=seg, position_ids=pos)
    # change a token in doc 0: doc 1 logits unaffected
    tokens2 = tokens.at[0, 2].set((tokens[0, 2] + 1) % 256)
    logits2, _ = model_forward(cfg, params, tokens2, segment_ids=seg, position_ids=pos)
    np.testing.assert_allclose(
        np.asarray(logits1[0, 8:]), np.asarray(logits2[0, 8:]), atol=1e-5
    )


@pytest.mark.parametrize("granularity,policy", [
    ("selective", "save_dots_except_logits"),
    ("selective", "save_dots_and_attn"),
    ("selective", "save_attn_only"),
    ("selective", "selective"),
    ("full", "full"),
    (None, "none"),
])
def test_remat_policies_compile_and_train(granularity, policy):
    """Every advertised remat policy (transformer._remat_policy) must
    produce a differentiable, loss-descending step — the CPU half of the
    PERF.md recompute sweep."""
    import jax
    import jax.numpy as jnp

    from megatron_llm_tpu.models import init_model_params, make_config
    from megatron_llm_tpu.models.language_model import loss_from_batch

    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, vocab_size=128, seq_length=32,
        max_position_embeddings=64, params_dtype="float32",
        use_flash_attn=False,
    )
    cfg.parallel.recompute_granularity = granularity
    cfg.training.remat_policy = policy
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0, 128)
    batch = {"tokens": tok[:, :-1], "labels": tok[:, 1:],
             "loss_mask": jnp.ones((2, 32), jnp.float32)}

    @jax.jit
    def step(p):
        loss, g = jax.value_and_grad(
            lambda q: loss_from_batch(cfg, q, batch)[0]
        )(p)
        return loss, jax.tree.map(lambda w, gg: w - 0.5 * gg, p, g)

    p = params
    first = last = None
    for _ in range(8):
        loss, p = step(p)
        if first is None:
            first = float(loss)
        last = float(loss)
    assert np.isfinite(last) and last < first


def test_embed_take_dispatch_and_chunk_policy(monkeypatch):
    """models/language_model.py:_embed_take — the schedule branch and the
    chunk-cap arithmetic: GPipe (whole-batch embed outside the tick loop)
    keeps the plain take/scatter; the 1F1B schedules get the matmul
    backward with a power-of-two chunk bounding the fp32 one-hot
    transient at 64 MiB."""
    import megatron_llm_tpu.models.language_model as lm

    calls = []
    real = lm._take_rows_matmul_bwd

    def spy(rows, chunk, dt):
        calls.append((rows, chunk))
        return real(rows, chunk, dt)

    monkeypatch.setattr(lm, "_take_rows_matmul_bwd", spy)
    table = jnp.zeros((32000, 8), jnp.float32)
    ids = jnp.zeros((2, 16), jnp.int32)

    def cfg_for(pp, schedule):
        cfg = make_config(
            "llama2", num_layers=2, hidden_size=32, num_attention_heads=2,
            num_attention_heads_kv=2, vocab_size=256,
            pipeline_model_parallel_size=pp, use_flash_attn=False)
        cfg.parallel.pipeline_schedule = schedule
        return cfg

    lm._embed_take(cfg_for(1, "1f1b"), table, ids)
    assert not calls  # pp=1: plain take
    lm._embed_take(cfg_for(2, "gpipe"), table, ids)
    assert not calls  # GPipe: plain take (scatter partitions fine there)
    lm._embed_take(cfg_for(2, "1f1b"), table, ids)
    # 64 MiB / (32000 rows * 4 B) = 524 -> power-of-two floor 512
    assert calls == [(32000, 512)]
    calls.clear()
    big = jnp.zeros((131072, 8), jnp.bfloat16)  # 128k vocab: fp32-sized cap
    lm._embed_take(cfg_for(2, "1f1b"), big, ids)
    assert calls == [(131072, 128)]


def test_matmul_backward_embedding_matches_take_vjp():
    """models/language_model.py:_take_rows_matmul_bwd — the pp-path
    embedding whose backward is a one-hot matmul instead of the take
    transpose's scatter-add (the round-5 partitioner-crash fix). Gradients
    must match jnp.take's vjp on BOTH the single-matmul path (small n)
    and the token-chunked path (n > 4096, incl. a non-4096-divisible n
    that must pick the largest fitting divisor, not fall back to one
    unbounded one-hot)."""
    import numpy as np

    from megatron_llm_tpu.models.language_model import _take_rows_matmul_bwd

    vocab, h = 512, 16
    table = jax.random.normal(jax.random.PRNGKey(0), (vocab, h))

    for shape in [(2, 64),        # single matmul
                  (2, 4096),      # n=8192: exact 4096 chunks
                  (2, 2304)]:     # n=4608: largest divisor <= 4096 is 2304
        ids = jax.random.randint(jax.random.PRNGKey(1), shape, 0, vocab)
        take = _take_rows_matmul_bwd(vocab, 4096, str(table.dtype))

        def loss_mm(t):
            return (take(t, ids).astype(jnp.float32) ** 2).sum()

        def loss_ref(t):
            return (jnp.take(t, ids, axis=0).astype(jnp.float32) ** 2).sum()

        g_mm = jax.grad(loss_mm)(table)
        g_ref = jax.grad(loss_ref)(table)
        np.testing.assert_allclose(np.asarray(g_mm), np.asarray(g_ref),
                                   atol=2e-4, rtol=2e-4)
