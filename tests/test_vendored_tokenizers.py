"""Vendored GPT-2 BPE + BERT WordPiece (tokenizer/vendored.py) — the
air-gapped tokenization capability the reference carries in
gpt2_tokenization.py/bert_tokenization.py. Tested against hand-built tiny
vocabularies with hand-derivable expected outputs, plus an HF
cross-check when a gpt2 tokenizer is locally cached (skipped offline),
and a no-HF-import guard proving the vendored path never touches
transformers."""

from __future__ import annotations

import json
import sys

import pytest


@pytest.fixture()
def gpt2_files(tmp_path):
    # tiny BPE: bytes for "low", "er", "lowest" etc; merges build "low"
    from megatron_llm_tpu.tokenizer.vendored import bytes_to_unicode

    b2u = bytes_to_unicode()

    def u(s):
        return "".join(b2u[b] for b in s.encode())

    merges = ["#version: 0.2", f"{u('l')} {u('o')}",
              f"{u('lo')} {u('w')}", f"{u('e')} {u('r')}"]
    toks = [u(x) for x in
            ["low", "lo", "l", "o", "w", "e", "r", "er", "s", "t", " ",
             " low"]]
    # " low" needs the merge (" l" not merged) — keep simple: vocab holds
    # every byte char we might emit
    vocab = {}
    for ch in set("".join(toks)):
        vocab.setdefault(ch, len(vocab))
    for t in toks:
        vocab.setdefault(t, len(vocab))
    vocab.setdefault("<|endoftext|>", len(vocab))
    vf = tmp_path / "vocab.json"
    mf = tmp_path / "merges.txt"
    vf.write_text(json.dumps(vocab))
    mf.write_text("\n".join(merges) + "\n")
    return str(vf), str(mf), vocab, u


def test_gpt2_bpe_merges_and_roundtrip(gpt2_files):
    from megatron_llm_tpu.tokenizer.vendored import GPT2BPETokenizer

    vf, mf, vocab, u = gpt2_files
    tok = GPT2BPETokenizer(vf, mf)
    ids = tok.tokenize("lower")
    # merges: l+o -> lo, lo+w -> low, e+r -> er  =>  ["low", "er"]
    assert ids == [vocab[u("low")], vocab[u("er")]]
    assert tok.detokenize(ids) == "lower"
    # unmerged word falls back to single (byte) tokens
    ids2 = tok.tokenize("lost")
    assert ids2 == [vocab[u("lo")], vocab[u("s")], vocab[u("t")]]
    assert tok.detokenize(tok.tokenize("lower lost")) == "lower lost"
    assert tok.eod == vocab["<|endoftext|>"]


def test_gpt2_bpe_oov_never_emits_eod(gpt2_files, tmp_path):
    """OOV pieces map to a dedicated unk id, NEVER eod — eod-as-unk would
    inject spurious document boundaries (round-3 advisor finding). And a
    vocab with no '<|endoftext|>' raises rather than silently repurposing
    the last vocab id as eod."""
    import json as _json

    from megatron_llm_tpu.tokenizer.vendored import GPT2BPETokenizer

    vf, mf, vocab, u = gpt2_files
    tok = GPT2BPETokenizer(vf, mf)
    # 'z' is not in the tiny vocab -> every emitted id must be unk, not eod
    ids = tok.tokenize("z")
    assert ids and all(i == tok.unk for i in ids)
    assert tok.unk != tok.eod
    assert tok.eod not in ids

    # explicit unk entry wins when present
    vocab2 = dict(vocab)
    vocab2["<unk>"] = len(vocab2)
    vf2 = tmp_path / "vocab_unk.json"
    vf2.write_text(_json.dumps(vocab2))
    tok2 = GPT2BPETokenizer(str(vf2), mf)
    assert tok2.unk == vocab2["<unk>"]
    assert tok2.tokenize("z") == [vocab2["<unk>"]]

    # missing <|endoftext|> is an error, not a silent fallback
    vocab3 = {k: v for k, v in vocab.items() if k != "<|endoftext|>"}
    vf3 = tmp_path / "vocab_noeod.json"
    vf3.write_text(_json.dumps(vocab3))
    tok3 = GPT2BPETokenizer(str(vf3), mf)
    with pytest.raises(ValueError, match="endoftext"):
        tok3.eod


def test_gpt2_bpe_matches_hf_when_available(tmp_path):
    try:
        from transformers import GPT2Tokenizer

        hf = GPT2Tokenizer.from_pretrained("gpt2", local_files_only=True)
    except Exception:
        pytest.skip("no locally cached gpt2 tokenizer (offline image)")
    vf = tmp_path / "vocab.json"
    mf = tmp_path / "merges.txt"
    vf.write_text(json.dumps(hf.encoder))
    mf.write_text("#version: 0.2\n" + "\n".join(
        " ".join(m) for m in hf.bpe_ranks))
    from megatron_llm_tpu.tokenizer.vendored import GPT2BPETokenizer

    ours = GPT2BPETokenizer(str(vf), str(mf))
    for text in ["Hello world!", "The    spaces,  and\tpunctuation?",
                 "naïve café ünïcödé", "don't they're we'll"]:
        assert ours.tokenize(text) == hf.encode(text), text


@pytest.fixture()
def wp_vocab(tmp_path):
    words = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
             "the", "quick", "brown", "fox", "un", "##aff", "##able",
             "run", "##ning", ",", ".", "!", "a"]
    vf = tmp_path / "vocab.txt"
    vf.write_text("\n".join(words) + "\n")
    return str(vf), {w: i for i, w in enumerate(words)}


def test_wordpiece_greedy_longest_match(wp_vocab):
    from megatron_llm_tpu.tokenizer.vendored import WordPieceTokenizer

    vf, v = wp_vocab
    tok = WordPieceTokenizer(vf, lower_case=True)
    assert tok.tokenize("unaffable") == [v["un"], v["##aff"], v["##able"]]
    assert tok.tokenize("running") == [v["run"], v["##ning"]]
    # punctuation split + lower-casing + accent stripping
    assert tok.tokenize("The Quick, brown!") == [
        v["the"], v["quick"], v[","], v["brown"], v["!"]]
    assert tok.tokenize("thé") == [v["the"]]  # NFD accent strip
    # unknown word -> [UNK] (whole word, per the algorithm)
    assert tok.tokenize("zzz") == [v["[UNK]"]]
    assert tok.cls == v["[CLS]"] and tok.mask == v["[MASK]"]
    assert tok.detokenize(tok.tokenize("unaffable running")) == \
        "unaffable running"


def test_vendored_path_needs_no_hf(gpt2_files, wp_vocab, monkeypatch):
    """build_tokenizer with local files must not import transformers or
    sentencepiece (the air-gapped guarantee)."""
    from megatron_llm_tpu.config.arguments import Config
    from megatron_llm_tpu.tokenizer.tokenizer import build_tokenizer

    for mod in ("transformers", "sentencepiece"):
        monkeypatch.setitem(sys.modules, mod, None)  # import -> TypeError

    vf, mf, vocab, _u = gpt2_files
    cfg = Config()
    cfg.data.tokenizer_type = "GPT2BPETokenizer"
    cfg.data.vocab_file = vf
    cfg.data.merge_file = mf
    tok = build_tokenizer(cfg)
    assert tok.vocab_size == len(vocab)

    wvf, wv = wp_vocab
    cfg2 = Config()
    cfg2.data.tokenizer_type = "BertWordPieceLowerCase"
    cfg2.data.vocab_file = wvf
    tok2 = build_tokenizer(cfg2)
    assert tok2.vocab_size == len(wv)
    assert tok2.tokenize("the fox") == [wv["the"], wv["fox"]]

def test_wordpiece_blank_line_gives_dense_ids(tmp_path):
    vf = tmp_path / "v.txt"
    vf.write_text("[PAD]\n[UNK]\n\nthe\nfox\n")  # interior blank line
    from megatron_llm_tpu.tokenizer.vendored import WordPieceTokenizer

    tok = WordPieceTokenizer(str(vf))
    assert tok.vocab_size == 4
    ids = tok.tokenize("the fox")
    assert ids == [2, 3] and max(ids) < tok.vocab_size


def test_gpt2_unknown_piece_falls_back_to_unk(gpt2_files):
    from megatron_llm_tpu.tokenizer.vendored import GPT2BPETokenizer

    vf, mf, vocab, _u = gpt2_files
    tok = GPT2BPETokenizer(vf, mf)
    ids = tok.tokenize("q")  # byte char absent from the tiny vocab
    assert ids == [tok.unk]
    assert tok.unk != tok.eod  # OOV must never look like a doc boundary
