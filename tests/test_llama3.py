"""Llama-3 family (beyond-reference): flag bundle, the "llama3" RoPE
frequency remap (HF ``rope_type: "llama3"``, Llama-3.1+), and HF config
round-tripping. The reference stops at CodeLlama's linear interpolation
(positional_embeddings.py:11); this family extends the same machinery."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.models import make_config
from megatron_llm_tpu.ops.rope import llama3_scale_freqs, precompute_freqs

L3_SCALING = dict(factor=8.0, low_freq_factor=1.0, high_freq_factor=4.0,
                  original_max_position=8192)


def _base_freqs(dim=128, theta=500_000.0):
    return 1.0 / (theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))


def test_family_bundle():
    cfg = make_config("llama3-8b")
    m = cfg.model
    assert m.rope_theta == 500_000.0
    assert m.num_attention_heads_kv == 8 and m.num_attention_heads == 32
    assert m.ffn_hidden_size == 14336
    assert m.use_rms_norm and m.glu_activation == "swiglu" and not m.use_bias
    from megatron_llm_tpu.models.language_model import padded_vocab_size
    assert padded_vocab_size(m.vocab_size, cfg) == 128256  # already 128-divisible


def test_family_invariants_enforced():
    with pytest.raises(ValueError, match="rotary"):
        make_config("llama3", num_layers=2, hidden_size=64,
                    num_attention_heads=4, vocab_size=256,
                    position_embedding_type="absolute")


def test_remap_piecewise():
    freqs = _base_freqs()
    out = np.asarray(llama3_scale_freqs(freqs, **L3_SCALING))
    base = np.asarray(freqs)
    wavelen = 2 * np.pi / base
    hi = wavelen < 8192 / 4.0   # well inside original context: untouched
    lo = wavelen > 8192 / 1.0   # beyond original context: pure interpolation
    assert hi.any() and lo.any()
    np.testing.assert_allclose(out[hi], base[hi], rtol=1e-6)
    np.testing.assert_allclose(out[lo], base[lo] / 8.0, rtol=1e-6)
    band = ~hi & ~lo
    assert ((out[band] >= base[band] / 8.0 - 1e-9)
            & (out[band] <= base[band] + 1e-9)).all()


def test_remap_matches_hf():
    """Cross-check against transformers' own llama3 rule when available."""
    try:
        from transformers import LlamaConfig
        from transformers.modeling_rope_utils import _compute_llama3_parameters
    except ImportError:
        pytest.skip("transformers rope utils not available")
    hf_cfg = LlamaConfig(
        hidden_size=512, num_attention_heads=4, rope_theta=500_000.0,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 8192},
    )
    try:
        hf_freqs, _ = _compute_llama3_parameters(hf_cfg, device="cpu")
    except Exception as e:  # signature drift across versions
        pytest.skip(f"HF helper signature mismatch: {e}")
    ours = np.asarray(llama3_scale_freqs(_base_freqs(), **L3_SCALING))
    np.testing.assert_allclose(ours, np.asarray(hf_freqs), rtol=1e-5)


def test_precompute_freqs_llama3_vs_linear():
    c3, s3 = precompute_freqs(64, 128, theta=500_000.0, scaling_factor=8.0,
                              scaling_type="llama3")
    cl, sl = precompute_freqs(64, 128, theta=500_000.0, scaling_factor=8.0,
                              scaling_type="linear")
    assert not np.allclose(np.asarray(c3), np.asarray(cl))
    # factor 1.0 under llama3 == unscaled (the remap is gated on factor)
    c1, _ = precompute_freqs(64, 128, theta=500_000.0, scaling_factor=1.0,
                             scaling_type="llama3")
    c0, _ = precompute_freqs(64, 128, theta=500_000.0)
    np.testing.assert_allclose(np.asarray(c1), np.asarray(c0))


def test_unknown_scaling_type_fails_loudly():
    with pytest.raises(ValueError, match="scaling_type"):
        precompute_freqs(64, 128, scaling_factor=8.0, scaling_type="yarn")


def test_hf_config_roundtrip():
    from weights_conversion.hf_to_native import config_from_hf
    from weights_conversion.native_to_hf import hf_config_from_native

    try:
        from transformers import LlamaConfig
    except ImportError:
        pytest.skip("transformers not available")
    src = LlamaConfig(
        num_hidden_layers=2, hidden_size=128, num_attention_heads=4,
        num_key_value_heads=2, intermediate_size=256, vocab_size=1024,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        rope_theta=500_000.0,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 8192},
    )
    cfg = config_from_hf(src, "llama3")
    m = cfg.model
    assert m.rope_scaling_type == "llama3"
    assert m.rope_scaling_factor == 8.0
    assert m.rope_llama3_high_freq_factor == 4.0
    back = hf_config_from_native(cfg, vocab_size=1024)
    rs = back.rope_scaling
    assert rs["rope_type"] == "llama3" and rs["factor"] == 8.0
    assert rs["original_max_position_embeddings"] == 8192


def test_forward_smoke():
    """Tiny llama3 model with the remap active: loss computes and is finite
    (drives make_rope_cache's scaling_type wiring end to end)."""
    from megatron_llm_tpu.models import init_model_params, loss_from_batch

    cfg = make_config("llama3", num_layers=2, hidden_size=128,
                      num_attention_heads=4, num_attention_heads_kv=2,
                      vocab_size=512, params_dtype="float32",
                      max_position_embeddings=128,
                      rope_scaling_type="llama3", rope_scaling_factor=8.0,
                      use_flash_attn=False)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 65), 0, 512)
    batch = {"tokens": tok[:, :-1], "labels": tok[:, 1:],
             "loss_mask": jnp.ones((2, 64))}
    loss, _ = loss_from_batch(cfg, params, batch)
    assert np.isfinite(float(loss))
