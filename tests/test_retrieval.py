"""Biencoder / ICT / REALM-index / ORQA / MSDP stacks (VERDICT missing #5:
reference biencoder_model.py, ict_dataset.py, realm_index.py, indexer.py,
pretrain_ict.py, tasks/orqa, tasks/msdp)."""

import json

import jax
import numpy as np
import pytest

from megatron_llm_tpu.config import Config, apply_architecture
from megatron_llm_tpu.data.ict_dataset import ICTDataset, build_blocks_mapping
from megatron_llm_tpu.data.indexed_dataset import make_builder, make_dataset
from megatron_llm_tpu.retrieval.biencoder import (
    biencoder_forward,
    ict_loss_from_batch,
    init_biencoder_params,
)
from megatron_llm_tpu.retrieval.index import BlockEmbedStore, MIPSIndex
from megatron_llm_tpu.retrieval.indexer import IndexBuilder


def bert_cfg(shared=False, proj=0):
    cfg = Config()
    apply_architecture(cfg, "bert")
    cfg.model.num_layers = 2
    cfg.model.hidden_size = 64
    cfg.model.num_attention_heads = 4
    cfg.model.vocab_size = 512
    cfg.model.max_position_embeddings = 64
    cfg.model.bert_binary_head = False
    cfg.data.seq_length = 32
    cfg.retriever.retriever_seq_length = 32
    cfg.retriever.biencoder_shared_query_context_model = shared
    cfg.retriever.biencoder_projection_dim = proj
    cfg.retriever.retriever_score_scaling = True
    cfg.training.params_dtype = "float32"
    cfg.training.use_flash_attn = False
    cfg.training.micro_batch_size = 4
    cfg.training.global_batch_size = 4
    cfg.training.train_iters = 4
    cfg.finalize(n_devices=1)
    return cfg


@pytest.fixture
def sentence_corpus(tmp_path):
    """Indexed dataset where items are sentences and docs group them."""
    prefix = str(tmp_path / "sents_text_document")
    rng = np.random.RandomState(0)
    builder = make_builder(prefix + ".bin", vocab_size=500)
    for _doc in range(8):
        for _sent in range(rng.randint(2, 6)):
            builder.add_item(rng.randint(5, 500, size=rng.randint(4, 12)))
        builder.end_document()
    builder.finalize(prefix + ".idx")
    return prefix


def test_blocks_mapping(sentence_corpus):
    ds = make_dataset(sentence_corpus)
    mapping = build_blocks_mapping(ds.sizes, ds.doc_idx, max_seq_length=24)
    assert len(mapping) > 0
    for start, end, doc, _bid in mapping:
        assert ds.doc_idx[doc] <= start < end <= ds.doc_idx[doc + 1]
        # a multi-sentence block fits the budget (single long sentences may
        # overflow and get truncated downstream, like the reference)
        if end - start > 1:
            assert ds.sizes[start:end].sum() <= 24
    # every multi-sentence doc is covered
    covered = {int(d) for _s, _e, d, _b in mapping}
    multi = {d for d in range(len(ds.doc_idx) - 1)
             if ds.doc_idx[d + 1] - ds.doc_idx[d] >= 2}
    assert multi <= covered


def test_ict_dataset_samples(sentence_corpus):
    ds = make_dataset(sentence_corpus)
    ict = ICTDataset(ds, None, max_seq_length=32, query_in_block_prob=0.0,
                     seed=3, use_titles=False, cls_id=1, sep_id=2, pad_id=0)
    s = ict[0]
    assert s["query_tokens"].shape == (32,) and s["context_tokens"].shape == (32,)
    assert s["query_tokens"][0] == 1  # CLS
    # query_in_block_prob=0: the query sentence is REMOVED from the context
    q_body = [t for t in s["query_tokens"] if t > 2]
    c_body = [t for t in s["context_tokens"] if t > 2]
    qs = " ".join(map(str, q_body))
    cs = " ".join(map(str, c_body))
    assert qs not in cs or len(q_body) == 0


def test_ict_loss_and_grads():
    cfg = bert_cfg(proj=16)
    params = init_biencoder_params(cfg, jax.random.PRNGKey(0))
    assert "query_model" in params and "context_model" in params
    rng = np.random.RandomState(0)
    batch = {
        "query_tokens": rng.randint(3, 512, (4, 32)),
        "query_pad_mask": np.ones((4, 32), np.int64),
        "context_tokens": rng.randint(3, 512, (4, 32)),
        "context_pad_mask": np.ones((4, 32), np.int64),
    }
    q, c = biencoder_forward(cfg, params, batch)
    assert q.shape == (4, 16) and c.shape == (4, 16)

    (loss, metrics), grads = jax.value_and_grad(
        lambda p: ict_loss_from_batch(cfg, p, batch), has_aux=True
    )(params)
    assert np.isfinite(float(loss))
    assert "top1_acc" in metrics
    gnorm = sum(float(np.abs(g).sum()) for g in jax.tree_util.tree_leaves(grads))
    assert gnorm > 0


def test_ict_shared_tower():
    cfg = bert_cfg(shared=True)
    params = init_biencoder_params(cfg, jax.random.PRNGKey(0))
    assert set(params) == {"shared_model"}
    batch = {
        "query_tokens": np.full((2, 32), 5), "query_pad_mask": np.ones((2, 32)),
        "context_tokens": np.full((2, 32), 5), "context_pad_mask": np.ones((2, 32)),
    }
    q, c = biencoder_forward(cfg, params, batch)
    np.testing.assert_allclose(q, c, atol=1e-6)  # same tower, same input


def test_bert_load_warm_start(tmp_path):
    """--bert_load warm-starts the towers from a BERT checkpoint
    (init_state_dict_from_bert analog)."""
    import orbax.checkpoint as ocp

    from megatron_llm_tpu.models import init_model_params

    cfg = bert_cfg(proj=8)
    bert_params = init_model_params(cfg, jax.random.PRNGKey(42))
    ckpt = tmp_path / "bert" / "release" / "params"
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(str(ckpt), bert_params)
    ckptr.wait_until_finished()  # async save; restore below needs it durable
    (tmp_path / "bert" / "latest_checkpointed_iteration.txt").write_text(
        "release")

    cfg.retriever.bert_load = str(tmp_path / "bert")
    params = init_biencoder_params(cfg, jax.random.PRNGKey(0))
    for tower in ("query_model", "context_model"):
        np.testing.assert_allclose(
            params[tower]["embedding"]["word_embeddings"],
            bert_params["embedding"]["word_embeddings"])
        assert "projection" in params[tower]
    # projections are fresh (not shared between towers)
    assert not np.allclose(params["query_model"]["projection"]["kernel"],
                           params["context_model"]["projection"]["kernel"])


def test_mips_index_and_store(tmp_path):
    rng = np.random.RandomState(1)
    embeds = rng.randn(50, 8).astype(np.float32)
    store = BlockEmbedStore(str(tmp_path / "emb.pkl"))
    store.add_block_data(np.arange(50), embeds)
    store.save()
    store2 = BlockEmbedStore(str(tmp_path / "emb.pkl"), load_from_path=True)
    assert len(store2) == 50

    index = MIPSIndex(8, store=store2, use_device=False)
    q = rng.randn(3, 8).astype(np.float32)
    scores, ids = index.search_mips_index(q, top_k=5)
    # the store keeps fp16 embeddings; brute-force against the same rounding
    brute = q @ embeds.astype(np.float16).astype(np.float32).T
    expect = np.argsort(-brute, axis=-1)[:, :5]
    np.testing.assert_array_equal(ids, expect)
    np.testing.assert_allclose(
        scores, np.take_along_axis(brute, expect, -1), rtol=1e-5)

    # device path agrees with numpy path
    index_dev = MIPSIndex(8, store=store2, use_device=True)
    s2, ids2 = index_dev.search_mips_index(q, top_k=5)
    np.testing.assert_array_equal(ids2, expect)


def test_store_shard_merge(tmp_path):
    path = str(tmp_path / "emb.pkl")
    for rank in range(2):
        shard = BlockEmbedStore(path, rank=rank)
        shard.add_block_data([rank * 10, rank * 10 + 1],
                             np.ones((2, 4)) * rank)
        shard.save_shard()
    merged = BlockEmbedStore(path)
    merged.merge_shards_and_save()
    final = BlockEmbedStore(path, load_from_path=True)
    assert sorted(final.embed_data) == [0, 1, 10, 11]


def test_index_builder(sentence_corpus, tmp_path):
    cfg = bert_cfg()
    cfg.retriever.embedding_path = str(tmp_path / "blocks.pkl")
    cfg.retriever.indexer_batch_size = 4
    params = init_biencoder_params(cfg, jax.random.PRNGKey(0))
    ds = make_dataset(sentence_corpus)
    ict = ICTDataset(ds, None, max_seq_length=32, use_titles=False,
                     cls_id=1, sep_id=2, pad_id=0)
    store = IndexBuilder(cfg, params, ict).build_and_save_index(log=lambda *_: None)
    assert len(store) == len(ict.mapping)
    dim = next(iter(store.embed_data.values())).shape[-1]
    assert dim == cfg.model.hidden_size


def test_orqa_evaluator(sentence_corpus, tmp_path):
    """End to end: evidence docs -> index -> question retrieval accuracy."""
    from tasks.orqa.evaluate import ORQAEvaluator
    from tasks.orqa.qa_utils import calculate_matches, has_answer

    assert has_answer(["forty two"], "the answer is Forty-Two indeed")
    assert not has_answer(["nothing"], "the answer is forty two")
    assert has_answer([r"forty.?two"], "it is forty-two", match_type="regex")

    stats = calculate_matches(
        {0: ("paris is the capital", ""), 1: ("berlin", "")},
        [["paris"]], [([1, 0], [0.9, 0.8])],
    )
    assert stats.top_k_hits == [0, 1]  # found at rank 2

    cfg = bert_cfg()
    params = init_biencoder_params(cfg, jax.random.PRNGKey(0))
    store = BlockEmbedStore()
    rng = np.random.RandomState(0)
    store.add_block_data(np.arange(4), rng.randn(4, cfg.model.hidden_size))

    evidence = tmp_path / "evidence.jsonl"
    evidence.write_text("\n".join(
        json.dumps({"id": i, "text": f"document {i} mentions answer{i}",
                    "title": f"t{i}"}) for i in range(4)
    ) + "\n")
    qa = tmp_path / "qa.jsonl"
    qa.write_text(json.dumps(
        {"question": "which doc mentions answer2?", "answers": ["answer2"]}
    ) + "\n")

    def tokenize(q):
        toks = np.zeros((32,), np.int64)
        ids = [1] + [3 + (hash(w) % 500) for w in q.split()][:30] + [2]
        toks[: len(ids)] = ids
        return toks, (toks != 0).astype(np.int64)

    ev = ORQAEvaluator(cfg, params, store, tokenize)
    results = ev.evaluate(str(qa), str(evidence), top_k=4)
    assert "top4_acc" in results and 0.0 <= results["top4_acc"] <= 100.0


def test_msdp_pipeline(tmp_path):
    from tasks.msdp.evaluate import evaluate_f1
    from tasks.msdp.metrics import F1Metric
    from tasks.msdp.preprocessing import process_dialogs
    from tasks.msdp.prompt import generate_samples

    p, r, f1 = F1Metric.compute_each_pair("the cat sat", "the cat stood")
    assert 0 < f1 < 1
    assert F1Metric.compute_each_pair("", "ref") == (0.0, 0.0, 0.0)

    dialogs = tmp_path / "dialogs.jsonl"
    dialogs.write_text(json.dumps({
        "topic": "cats",
        "turns": ["do cats purr?", "yes cats purr when happy",
                  "why?", "vibration of the larynx"],
        "knowledge": ["cats purr via larynx", "larynx vibrates"],
    }) + "\n")
    test_file, ref_file = tmp_path / "test.txt", tmp_path / "refs.txt"
    n = process_dialogs(str(dialogs), str(test_file), str(ref_file))
    assert n == 2
    assert test_file.read_text().splitlines()[1].count("\t") == 2

    # knowledge stage with a fake LM
    kprompts = tmp_path / "kprompts.jsonl"
    kprompts.write_text(json.dumps(
        {"cats do cats purr?": ["( example ) cats => cats purr"]}) + "\n")
    out = tmp_path / "gen.txt"
    n = generate_samples(
        lambda text, _n: text + " generated knowledge\nrest",
        str(kprompts), "knowledge", str(test_file), str(out))
    assert n == 2
    assert all(line == "generated knowledge"
               for line in out.read_text().splitlines())

    # response stage + F1 eval, conditioned on stage 1's generated knowledge
    rprompt = tmp_path / "rprompt.txt"
    rprompt.write_text("Example response prompt\n")
    out2 = tmp_path / "resp.txt"
    seen_inputs = []
    generate_samples(
        lambda text, _n: (seen_inputs.append(text)
                          or text + " yes cats purr when happy\nmore"),
        str(rprompt), "response", str(test_file), str(out2),
        knowledge_file=str(out))
    assert all("generated knowledge" in t for t in seen_inputs)
    _p, _r, f1 = evaluate_f1(str(out2), str(ref_file))
    assert f1 > 0.3


def test_orqa_supervised_finetune(tmp_path):
    """DPR-style supervised retriever finetuning (tasks/orqa/supervised)."""
    from tasks.orqa.supervised import (
        OpenRetrievalSupervisedDataset,
        finetune_orqa,
        load_dpr_json,
        orqa_supervised_loss,
    )

    rng = np.random.RandomState(0)
    records = []
    for i in range(8):
        words = lambda: " ".join(str(x) for x in rng.randint(3, 500, 8))
        records.append({
            "question": words(),
            "answers": ["x"],
            "positive_ctxs": [{"text": words(), "title": str(i)}],
            "hard_negative_ctxs": [{"text": words()}, {"text": words()}],
        })
    path = tmp_path / "nq.json"
    path.write_text(json.dumps(records))
    assert len(load_dpr_json(str(path))) == 8

    cfg = bert_cfg(proj=16)
    tokenize = lambda s: [int(t) % 512 for t in s.split()]
    ds = OpenRetrievalSupervisedDataset(
        records, tokenize, 32, n_hard_negatives=1,
        cls_id=1, sep_id=2, pad_id=0, num_samples=100)
    s = ds[0]
    assert s["context_tokens"].shape == (2, 32)  # positive + 1 negative

    from megatron_llm_tpu.retrieval.biencoder import init_biencoder_params
    from tasks.orqa.supervised import supervised_collator

    params = init_biencoder_params(cfg, jax.random.PRNGKey(0))
    batch = supervised_collator([ds[i] for i in range(4)])
    (loss, metrics), grads = jax.value_and_grad(
        lambda p: orqa_supervised_loss(cfg, p, batch), has_aux=True
    )(params)
    assert np.isfinite(float(loss)) and "rank1_acc" in metrics
    assert sum(float(np.abs(g).sum())
               for g in jax.tree_util.tree_leaves(grads)) > 0

    # end to end through the training driver
    cfg.data.tokenizer_type = "NullTokenizer"
    cfg.model.vocab_size = 512
    cfg.training.train_iters = 2
    cfg.training.eval_iters = 1
    cfg.training.eval_interval = 100
    ds2 = OpenRetrievalSupervisedDataset(
        records, tokenize, 32, cls_id=1, sep_id=2, pad_id=0, num_samples=100)
    result = finetune_orqa(cfg, ds2)
    assert result["iteration"] == 2
    assert np.isfinite(float(result["last_metrics"]["lm loss"]))


def test_pretrain_ict_end_to_end(sentence_corpus, tmp_path):
    """The pretrain_ict.py entry trains on the CPU mesh and reports
    retrieval accuracy metrics."""
    import pretrain_ict

    result = pretrain_ict.main([
        "--data_path", sentence_corpus,
        "--tokenizer_type", "NullTokenizer",
        "--vocab_size", "512",
        "--num_layers", "2", "--hidden_size", "64",
        "--num_attention_heads", "4",
        "--max_position_embeddings", "64",
        "--retriever_seq_length", "32",
        "--seq_length", "32",
        "--params_dtype", "float32",
        "--use_flash_attn", "false",
        "--micro_batch_size", "4", "--global_batch_size", "4",
        "--data_parallel_size", "1",
        "--train_iters", "3", "--eval_iters", "1", "--eval_interval", "100",
        "--lr", "1e-3",
        "--biencoder_projection_dim", "16",
    ])
    assert result["iteration"] == 3
    assert np.isfinite(float(result["last_metrics"]["lm loss"]))
    # top-k retrieval accuracies flow through the eval path; the metric
    # computation itself is asserted in test_ict_loss_and_grads


def test_load_evidence_tsv(tmp_path):
    """DPR psgs_w100.tsv format (reference orqa_wiki_dataset.py input)."""
    from tasks.orqa.evaluate import load_evidence

    tsv = tmp_path / "wiki.tsv"
    tsv.write_text("id\ttext\ttitle\n1\tparis is in france\tParis\n"
                   "2\tberlin text\tBerlin\n")
    docs = load_evidence(str(tsv))
    assert docs[1] == ("paris is in france", "Paris")
    assert len(docs) == 2
