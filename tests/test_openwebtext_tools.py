"""Corpus-cleaning tools (reference tools/openwebtext analogs)."""

import json
import subprocess
import sys

REPO = __file__.rsplit("/tests/", 1)[0]


def run(script, *args):
    return subprocess.run(
        [sys.executable, f"{REPO}/tools/openwebtext/{script}", *args],
        capture_output=True, text=True, timeout=120,
    )


def test_blacklist_urls(tmp_path):
    urls = tmp_path / "urls.txt"
    urls.write_text(
        "http://good.example.org/page\n"
        "http://bad.example.com/x\n"
        "http://sub.bad.example.com/y\n"       # subdomain of blacklisted
        "http://good.example.org/page\n"        # duplicate
        "http://other.org/casino-games\n"       # keyword
    )
    (tmp_path / "domains.txt").write_text("bad.example.com\n")
    (tmp_path / "keywords.txt").write_text("casino\n")
    out = tmp_path / "clean.txt"
    r = run("blacklist_urls.py", str(urls), str(out),
            "--domain_blacklist", str(tmp_path / "domains.txt"),
            "--keyword_blacklist", str(tmp_path / "keywords.txt"))
    assert r.returncode == 0, r.stderr
    assert out.read_text().splitlines() == ["http://good.example.org/page"]


def test_find_duplicates(tmp_path):
    base = "the quick brown fox jumps over the lazy dog " * 20
    docs = [
        {"id": "a", "text": base},
        {"id": "b", "text": base + "extra tail words here"},  # near-dup of a
        {"id": "c", "text": "completely different content " * 30},
    ]
    src = tmp_path / "corpus.jsonl"
    src.write_text("\n".join(json.dumps(d) for d in docs) + "\n")
    out = tmp_path / "dups.txt"
    r = run("find_duplicates.py", str(src), str(out), "--threshold", "0.5")
    assert r.returncode == 0, r.stderr
    groups = [set(line.split("\t")) for line in out.read_text().splitlines()]
    assert {"a", "b"} in groups
    assert all("c" not in g for g in groups)


def test_filter_ngrams(tmp_path):
    task = tmp_path / "task.jsonl"
    task.write_text(json.dumps(
        {"text": "the secret evaluation answer is forty two exactly"}
    ) + "\n")
    corpus = tmp_path / "corpus.jsonl"
    corpus.write_text(
        json.dumps({"text": "clean document " * 20}) + "\n"
        + json.dumps({"text": "leaked: the secret evaluation answer is forty "
                              "two exactly, plus more"}) + "\n"
    )
    out = tmp_path / "clean.jsonl"
    r = run("filter_ngrams.py", str(corpus), str(out),
            "--task_files", str(task), "--ngram_n", "5")
    assert r.returncode == 0, r.stderr
    lines = out.read_text().splitlines()
    assert len(lines) == 1 and "clean document" in lines[0]


def test_add_id(tmp_path):
    src = tmp_path / "c.jsonl"
    src.write_text(json.dumps({"text": "a"}) + "\n" + json.dumps({"text": "b"}) + "\n")
    out = tmp_path / "o.jsonl"
    r = run("add_id.py", str(src), str(out), "--id_prefix", "owt")
    assert r.returncode == 0, r.stderr
    docs = [json.loads(x) for x in out.read_text().splitlines()]
    assert [d["id"] for d in docs] == ["owt-0", "owt-1"]


def test_group_and_remove_duplicates(tmp_path):
    pairs = tmp_path / "pairs.jsonl"
    pairs.write_text(
        json.dumps({"http://a": [{"http://b": 0.9}, {"http://x": 0.1}]}) + "\n"
        + json.dumps({"http://b": [{"http://c": 0.8}]}) + "\n"
        + json.dumps({"http://solo": []}) + "\n"
    )
    groups = tmp_path / "groups.jsonl"
    r = run("group_duplicate_url.py", str(pairs), str(groups))
    assert r.returncode == 0, r.stderr
    gs = [json.loads(x) for x in groups.read_text().splitlines()]
    assert gs == [["http://a", "http://b", "http://c"]]  # transitive a-b-c

    corpus = tmp_path / "corpus.jsonl"
    corpus.write_text("\n".join(
        json.dumps({"url": u, "text": u}) for u in
        ["http://a", "http://b", "http://c", "http://x", "http://solo"]
    ) + "\n")
    out = tmp_path / "dedup.jsonl"
    r = run("remove_group_duplicates.py", str(groups), str(corpus), str(out))
    assert r.returncode == 0, r.stderr
    kept = [json.loads(x)["url"] for x in out.read_text().splitlines()]
    # first group member kept, b/c removed, non-group docs kept
    assert kept == ["http://a", "http://x", "http://solo"]


def test_merge_jsons(tmp_path):
    d = tmp_path / "shards"
    d.mkdir()
    (d / "a.json").write_text(json.dumps({"text": "1"}) + "\n")
    (d / "b.jsonl").write_text(json.dumps({"text": "2"}) + "\n")
    out = tmp_path / "merged.jsonl"
    r = run("merge_jsons.py", "--json_path", str(d), "--output_file", str(out))
    assert r.returncode == 0, r.stderr
    assert len(out.read_text().splitlines()) == 2


def test_cleanup_fix_dataset(tmp_path):
    src = tmp_path / "c.jsonl"
    long_text = "the quick brown fox and the lazy dog went to the market " * 12
    src.write_text(
        json.dumps({"text": "short javascript snippet"}) + "\n"
        + json.dumps({"text": long_text + "trailing   spaces\n\n\n\nend"}) + "\n"
    )
    out = tmp_path / "o.jsonl"
    r = run("cleanup_fix_dataset.py", str(src), str(out),
            "--tasks", "remove_256_javascript,general_cleaning")
    assert r.returncode == 0, r.stderr
    docs = [json.loads(x) for x in out.read_text().splitlines()]
    assert len(docs) == 1
    assert "   " not in docs[0]["text"] and "\n\n\n" not in docs[0]["text"]


def test_cleanup_dataset(tmp_path):
    corpus = tmp_path / "corpus.jsonl"
    corpus.write_text(
        json.dumps({"text": "word " * 200}) + "\n"
        + json.dumps({"text": "too short"}) + "\n"
    )
    out = tmp_path / "clean.jsonl"
    r = run("cleanup_dataset.py", str(corpus), str(out), "--min_words", "100")
    assert r.returncode == 0, r.stderr
    assert len(out.read_text().splitlines()) == 1


def test_rich_corpus_prose_filter():
    """make_e2e_corpus --rich harvests docstring PROSE only: parameter
    tables, doctests and code-ish lines are dropped, real sentences kept
    (round-3 VERDICT item 8 support)."""
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    from make_e2e_corpus import _prose_paragraphs

    doc = (
        "Compute the arithmetic mean along the specified axis, returning "
        "the average of the array elements over the given axis. The "
        "average is taken over the flattened array by default.\n\n"
        ">>> np.mean([1, 2, 3])\n2.0\n\n"
        "Parameters\n----------\naxis : int\n\n"
        "x : array_like\n    Input values.\n\n"
        "This second paragraph is genuine prose as well, long enough to "
        "pass the filter, and it contains multiple sentences. That is "
        "exactly what the harvester should keep for the corpus."
    )
    paras = list(_prose_paragraphs(doc))
    assert len(paras) == 2, paras
    assert all(". " in p for p in paras)
    assert not any(">>>" in p or "----" in p for p in paras)
