"""True 1F1B schedule (parallel/pipeline.py pipeline_1f1b_loss_and_grads):
loss/grads must match the GPipe autodiff path and the unsharded reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.core.parallel_state import build_mesh, global_mesh
from megatron_llm_tpu.models import init_model_params, make_config
from megatron_llm_tpu.models.language_model import loss_from_batch
from megatron_llm_tpu.parallel.pipeline import (
    pipeline_1f1b_loss_and_grads,
    pipeline_loss_fn,
)
from megatron_llm_tpu.parallel.tp import param_shardings


def _cfg(pp=2, cp=1, tp=1, num_micro=4, schedule="1f1b"):
    cfg = make_config(
        "llama2",
        num_layers=4, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, vocab_size=256, seq_length=32,
        max_position_embeddings=64, params_dtype="float32",
        use_flash_attn=False,
        pipeline_model_parallel_size=pp, tensor_model_parallel_size=tp,
        context_parallel_size=cp, pipeline_schedule=schedule,
    )
    cfg.parallel.data_parallel_size = 1
    cfg.parallel.num_micro_batches = num_micro
    return cfg


def _batch(gbs=8, seq=32, vocab=256, seed=1):
    tok = jax.random.randint(jax.random.PRNGKey(seed), (gbs, seq + 1), 0, vocab)
    return {
        "tokens": jnp.asarray(tok[:, :-1]),
        "labels": jnp.asarray(tok[:, 1:]),
        "loss_mask": jnp.ones((gbs, seq), jnp.float32),
    }


@pytest.mark.parametrize("pp,num_micro", [(2, 4), (4, 8), (2, 2)])
def test_1f1b_matches_reference_grads(eight_devices, pp, num_micro):
    cfg = _cfg(pp=pp, num_micro=num_micro)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    batch = _batch()

    # unsharded reference: plain loss + autodiff
    cfg1 = _cfg(pp=1, num_micro=1)
    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: loss_from_batch(cfg1, p, batch)[0]
    )(params)

    mesh = build_mesh(pipeline_model_parallel_size=pp, data_parallel_size=1,
                      devices=eight_devices[:pp])
    with global_mesh(mesh):
        sharded = jax.device_put(params, param_shardings(mesh, params))
        loss, grads = jax.jit(
            lambda p, b: pipeline_1f1b_loss_and_grads(cfg, mesh, p, b)
        )(sharded, batch)

    assert abs(float(ref_loss) - float(loss)) < 1e-5, (ref_loss, loss)
    ref_flat = {
        jax.tree_util.keystr(k): v
        for k, v in jax.tree_util.tree_leaves_with_path(ref_grads)
    }
    got_flat = {
        jax.tree_util.keystr(k): v
        for k, v in jax.tree_util.tree_leaves_with_path(grads)
    }
    assert set(ref_flat) == set(got_flat)
    for key in ref_flat:
        np.testing.assert_allclose(
            np.asarray(ref_flat[key]), np.asarray(got_flat[key]),
            atol=2e-4, rtol=2e-4, err_msg=key,
        )


def test_1f1b_matches_gpipe(eight_devices):
    """Both schedules, same mesh, identical loss and grads."""
    pp, num_micro = 2, 4
    cfg = _cfg(pp=pp, num_micro=num_micro)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    batch = _batch()
    mesh = build_mesh(pipeline_model_parallel_size=pp, data_parallel_size=1,
                      devices=eight_devices[:pp])
    with global_mesh(mesh):
        sharded = jax.device_put(params, param_shardings(mesh, params))
        loss_a, grads_a = jax.jit(
            lambda p, b: pipeline_1f1b_loss_and_grads(cfg, mesh, p, b)
        )(sharded, batch)
        loss_b, grads_b = jax.jit(
            jax.value_and_grad(
                lambda p: pipeline_loss_fn(cfg, mesh, p, _batch())[0]
            )
        )(sharded)
    assert abs(float(loss_a) - float(loss_b)) < 1e-5
    for a, b in zip(jax.tree_util.tree_leaves(grads_a),
                    jax.tree_util.tree_leaves(grads_b)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


def test_1f1b_with_cp_and_tp(eight_devices):
    """pp=2 x cp=2 x tp=2 through the full train step with 1f1b schedule."""
    from megatron_llm_tpu.training_step import make_jitted_train_step

    results = {}
    batch = _batch(gbs=4)
    for name, (pp, cp, tp) in {"single": (1, 1, 1), "pp2cp2tp2": (2, 2, 2)}.items():
        cfg = _cfg(pp=pp, cp=cp, tp=tp, num_micro=2 if pp > 1 else 1)
        cfg.training.global_batch_size = 4
        cfg.training.micro_batch_size = 2 if pp > 1 else 4
        mesh = build_mesh(
            pipeline_model_parallel_size=pp, context_parallel_size=cp,
            tensor_model_parallel_size=tp, data_parallel_size=1,
            devices=eight_devices[: pp * cp * tp],
        )
        params = init_model_params(cfg, jax.random.PRNGKey(0))
        with global_mesh(mesh):
            step, _o, sh = make_jitted_train_step(cfg, mesh, params)
            p = jax.device_put(params, sh["params"])
            o = jax.device_put(sh["opt_state_value"], sh["opt_state"])
            b = sh["place_batch"](batch)
            p, o, m = step(p, o, b, jnp.zeros((), jnp.int32))
            results[name] = float(m["lm loss"])
    assert abs(results["single"] - results["pp2cp2tp2"]) < 2e-4, results


def test_1f1b_pp_vocab_head_flag_parity(eight_devices):
    """pp_vocab_parallel_head True vs False: same loss and grads.

    The flag defaults to True (pipeline.py:399-460 shards the head's
    vocab dim over the pp axis and runs vocab-parallel CE across stages),
    silently changing the numerics/memory profile of every 1F1B GPT run —
    so both paths are pinned EXPLICITLY here, against each other and
    against the unsharded reference (round-3 advisor finding)."""
    pp, num_micro = 2, 4
    batch = _batch()
    base = _cfg(pp=1, num_micro=1)
    params = init_model_params(base, jax.random.PRNGKey(0))
    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: loss_from_batch(base, p, batch)[0]
    )(params)

    out = {}
    for flag in (True, False):
        cfg = _cfg(pp=pp, num_micro=num_micro)
        cfg.parallel.pp_vocab_parallel_head = flag
        mesh = build_mesh(pipeline_model_parallel_size=pp,
                          data_parallel_size=1, devices=eight_devices[:pp])
        with global_mesh(mesh):
            sharded = jax.device_put(params, param_shardings(mesh, params))
            # per-flag compile is deliberate: the test compares the two
            # head variants' programs
            out[flag] = jax.jit(  # graftcheck: noqa[recompile-hazard]
                lambda p, b, cfg=cfg, mesh=mesh:
                pipeline_1f1b_loss_and_grads(cfg, mesh, p, b)
            )(sharded, batch)

    for flag, (loss, grads) in out.items():
        assert abs(float(ref_loss) - float(loss)) < 1e-5, (flag, ref_loss, loss)
        for a, b in zip(jax.tree_util.tree_leaves(ref_grads),
                        jax.tree_util.tree_leaves(grads)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-4,
                                       err_msg=f"pp_vocab_parallel_head={flag}")
    # and directly against each other, tighter than via the reference
    la, ga = out[True]
    lb, gb = out[False]
    assert abs(float(la) - float(lb)) < 1e-5
    for a, b in zip(jax.tree_util.tree_leaves(ga),
                    jax.tree_util.tree_leaves(gb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)
