"""Tensor-parallel named-mesh end-to-end tests (ISSUE 6).

Runs on the conftest 8-virtual-CPU-device mesh. Gates:

* the compat shim (parallel/compat.py) resolves modern shard_map semantics
  on the pinned jax — partial-manual regions, nesting, and the
  data-carried ``axis_index`` workaround;
* parallel/tp.py's param/batch rules land on real arrays (qkv
  column-parallel, fc2/dense row-parallel, vocab-parallel embedding) and
  degrade gracefully on a single-chip mesh;
* tp=1 vs tp=4 forward logits and train-step losses agree within the
  documented tolerance (row-parallel contractions reorder reductions —
  nothing else may drift), and the compiled tp>1 step really contains the
  all-reduce collectives the tp.py docstring promises;
* the engine decodes identical token streams from a tp-sharded
  ``PagedKVPool`` (heads-dim sharding), with the block tables host-side;
* the linter forbids direct jax shard_map imports outside compat.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from megatron_llm_tpu.core import parallel_state as ps
from megatron_llm_tpu.models import init_model_params, make_config
from megatron_llm_tpu.parallel import compat
from megatron_llm_tpu.parallel.tp import (
    batch_shardings,
    param_partition_specs,
    param_shardings,
)

VOCAB = 64


class ToyTokenizer:
    eod = 0
    bos = 1
    vocab_size = VOCAB

    def tokenize(self, text):
        return [2 + (ord(c) % (VOCAB - 2)) for c in text]

    def detokenize(self, ids):
        return "".join(chr(97 + (i % 26)) for i in ids if i >= 2)


@pytest.fixture(scope="module")
def toy_model():
    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=4, ffn_hidden_size=128, seq_length=64,
        max_position_embeddings=256, vocab_size=VOCAB,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype="float32", use_flash_attn=False,
    )
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


# ---------------------------------------------------------------------------
# compat shim
# ---------------------------------------------------------------------------


def test_compat_partial_manual_axis_index_and_nesting(eight_devices):
    """Partial-manual region: ppermute works, compat.axis_index returns the
    data-carried coordinate, a nested inner region binds the remaining
    axes, and grads flow through the whole sandwich."""
    mesh = ps.build_mesh(tensor_model_parallel_size=2,
                         pipeline_model_parallel_size=2,
                         data_parallel_size=2, devices=eight_devices)
    x = jnp.arange(8.0).reshape(2, 4)

    def inner_fn(a):
        return jax.lax.psum(a * a, ps.TP_AXIS)

    def body(a):
        am = compat.get_abstract_mesh()
        assert not am.empty
        assert set(am.manual_axes) == {ps.PP_AXIS, ps.CP_AXIS}
        stage = compat.axis_index(ps.PP_AXIS)
        auto = set(am.axis_names) - set(am.manual_axes)
        inner = compat.shard_map(
            inner_fn, mesh=am, in_specs=(P(None, ps.TP_AXIS),),
            out_specs=P(None, None), axis_names=auto, check_vma=False)
        perm = [(i, (i + 1) % 2) for i in range(2)]
        rolled = jax.lax.ppermute(a, ps.PP_AXIS, perm)
        return inner(rolled) + stage.astype(jnp.float32)

    fn = compat.shard_map(
        body, mesh=mesh, in_specs=(P(),), out_specs=P(ps.PP_AXIS, None),
        axis_names={ps.PP_AXIS, ps.CP_AXIS}, check_vma=False)
    with ps.global_mesh(mesh):
        out = jax.jit(fn)(x)
        grads = jax.jit(jax.grad(lambda a: fn(a).sum()))(x)
    # the inner psum over tp sums the two column shards of x^2; each pp
    # stage adds its (data-carried) stage index; out stacks the stages
    xsq = np.asarray(x * x)
    col_sum = xsq[:, :2] + xsq[:, 2:]
    expect = np.concatenate([col_sum + s for s in (0.0, 1.0)], 0)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)
    # loss = sum over both stages of sum(x^2)  =>  d/dx = 2 * 2x
    np.testing.assert_allclose(np.asarray(grads), 4.0 * np.asarray(x),
                               rtol=1e-6)


def test_compat_axis_index_outside_region_falls_back(eight_devices):
    """Full-manual region: compat.axis_index == lax.axis_index."""
    mesh = ps.build_mesh(data_parallel_size=8, devices=eight_devices)
    fn = compat.shard_map(
        lambda: compat.axis_index(ps.DP_AXIS)[None],
        mesh=mesh, in_specs=(), out_specs=P(ps.DP_AXIS), check_vma=False)
    with ps.global_mesh(mesh):
        out = jax.jit(fn)()
    np.testing.assert_array_equal(np.asarray(out), np.arange(8))


# ---------------------------------------------------------------------------
# sharding rules on real arrays
# ---------------------------------------------------------------------------


def test_param_specs_canonical_rules(toy_model):
    cfg, params = toy_model
    specs = param_partition_specs(params)
    flat = {
        tuple(getattr(k, "key", getattr(k, "name", str(k))) for k in path): s
        for path, s in jax.tree_util.tree_leaves_with_path(specs)
    }

    def find(*frag):
        hits = [s for names, s in flat.items()
                if all(f in names for f in frag)]
        assert hits, (frag, list(flat)[:10])
        return hits

    # column-parallel qkv: fused head dim (last axis) over tp
    for s in find("qkv", "kernel"):
        assert tuple(s)[-1] == ps.TP_AXIS, s
    # row-parallel attention output: input (head) dim over tp, bias repl
    for s in find("dense", "kernel"):
        assert ps.TP_AXIS in tuple(s) and tuple(s)[-1] != ps.TP_AXIS, s
    # vocab-parallel embedding
    for s in find("word_embeddings"):
        assert tuple(s)[0] == ps.TP_AXIS, s


def test_param_shardings_land_on_device(toy_model, eight_devices):
    cfg, params = toy_model
    mesh = ps.build_mesh(tensor_model_parallel_size=4, data_parallel_size=2,
                         devices=eight_devices)
    placed = jax.device_put(params, param_shardings(mesh, params))
    n_tp = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(placed):
        spec = leaf.sharding.spec
        flat = [x for part in spec if part is not None
                for x in (part if isinstance(part, tuple) else (part,))]
        if ps.TP_AXIS in flat:
            n_tp += 1
            # a genuinely split leaf: per-device shard is smaller
            shard_shape = leaf.sharding.shard_shape(leaf.shape)
            assert int(np.prod(shard_shape)) < int(np.prod(leaf.shape))
    assert n_tp >= 4  # qkv + dense + fc1 + fc2 at least


def test_single_chip_degradation(toy_model):
    """A 1-device mesh: every spec still applies, every shard covers the
    whole array — same model code, no resharding, no collectives."""
    cfg, params = toy_model
    mesh = ps.build_mesh(devices=jax.devices()[:1])
    placed = jax.device_put(params, param_shardings(mesh, params))
    for leaf in jax.tree_util.tree_leaves(placed):
        assert leaf.sharding.shard_shape(leaf.shape) == leaf.shape
    b = {"tokens": np.ones((2, 16), np.int32),
         "labels": np.ones((2, 16), np.int32),
         "loss_mask": np.ones((2, 16), np.float32)}
    sh = batch_shardings(cfg, mesh, b)
    for k, s in sh.items():
        assert s.shard_shape(b[k].shape) == b[k].shape


# ---------------------------------------------------------------------------
# tp=1 vs tp=4 forward + train-step parity, collective presence
# ---------------------------------------------------------------------------


def _forward_logits(cfg, params, tokens, mesh):
    from megatron_llm_tpu.models.language_model import (
        make_rope_cache,
        model_forward,
    )

    with ps.global_mesh(mesh):
        placed = jax.device_put(params, param_shardings(mesh, params))
        tok = jax.device_put(
            jnp.asarray(tokens), NamedSharding(mesh, P()))

        @jax.jit
        def fwd(p, t):
            return model_forward(cfg, p, t, rope_cache=make_rope_cache(cfg))

        out = fwd(placed, tok)
        logits = out[0] if isinstance(out, tuple) else out
        return np.asarray(logits)


def test_tp4_logits_match_tp1(toy_model, eight_devices):
    cfg, params = toy_model
    tokens = np.random.RandomState(0).randint(2, VOCAB, (2, 32)).astype(
        np.int32)
    mesh1 = ps.build_mesh(devices=eight_devices[:1])
    mesh4 = ps.build_mesh(tensor_model_parallel_size=4,
                          data_parallel_size=1, devices=eight_devices[:4])
    l1 = _forward_logits(cfg, params, tokens, mesh1)
    l4 = _forward_logits(cfg, params, tokens, mesh4)
    # row-parallel contractions reorder fp32 sums; everything else is
    # identical — the tolerance documents that bound
    np.testing.assert_allclose(l1, l4, atol=2e-5, rtol=2e-5)


def test_tp_train_step_sharded_and_collectives(toy_model, eight_devices):
    """One jitted train step at tp=4: params stay sharded through the
    update, the loss matches tp=1, and the compiled program contains the
    all-reduces GSPMD inserted for the row-parallel contractions."""
    from megatron_llm_tpu.core import rng as rng_mod
    from megatron_llm_tpu.training_step import make_jitted_train_step

    losses, hlos = {}, {}
    for tp in (1, 4):
        # vocab 512 pads identically at tp=1 and tp=4 (padded vocab is a
        # function of make_vocab_size_divisible_by * tp — a 64-vocab toy
        # would train against a larger padded softmax at tp=4 and the
        # losses would legitimately differ)
        cfg = make_config(
            "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
            num_attention_heads_kv=4, ffn_hidden_size=128, seq_length=64,
            max_position_embeddings=256, vocab_size=512,
            hidden_dropout=0.0, attention_dropout=0.0,
            params_dtype="float32", use_flash_attn=False,
        )
        cfg.parallel.tensor_model_parallel_size = tp
        cfg.parallel.data_parallel_size = 1
        mesh = ps.build_mesh(tensor_model_parallel_size=tp,
                             data_parallel_size=1,
                             devices=eight_devices[:tp])
        with ps.global_mesh(mesh):
            key = rng_mod.init_key(7)
            p_shard = param_shardings(
                mesh, jax.eval_shape(lambda k: init_model_params(cfg, k),
                                     key))
            # per-tp-layout init compile is deliberate (parity matrix)
            params = jax.jit(  # graftcheck: noqa[recompile-hazard]
                lambda k: init_model_params(cfg, k),
                out_shardings=p_shard)(key)
            step_fn, optimizer, shardings = make_jitted_train_step(
                cfg, mesh, params)
            opt_state = optimizer.init(params)
            rng = np.random.RandomState(1)
            batch = {
                "tokens": rng.randint(2, 512, (4, 64)).astype(np.int32),
                "labels": rng.randint(2, 512, (4, 64)).astype(np.int32),
                "loss_mask": np.ones((4, 64), np.float32),
            }
            placed = shardings["place_batch"](batch)
            lr = jnp.float32(1e-3)
            hlos[tp] = step_fn.lower(
                params, opt_state, placed, lr).compile().as_text()
            new_params, _, metrics = step_fn(params, opt_state, placed, lr)
            losses[tp] = float(metrics["lm loss"])
            if tp > 1:
                qkv_leaves = [
                    (path, leaf) for path, leaf in
                    jax.tree_util.tree_leaves_with_path(new_params)
                    if any("qkv" == getattr(k, "key", None) for k in path)
                ]
                assert qkv_leaves
                for _, leaf in qkv_leaves:
                    shard = leaf.sharding.shard_shape(leaf.shape)
                    assert shard[-1] == leaf.shape[-1] // tp, (
                        "updated qkv kernel lost its tp sharding")
    assert abs(losses[1] - losses[4]) < 5e-4, losses
    assert hlos[4].count("all-reduce") > 0, "tp=4 step has no all-reduces"


# ---------------------------------------------------------------------------
# engine: tp-sharded PagedKVPool decode parity
# ---------------------------------------------------------------------------


def _run_engine(cfg, params, mesh, seeds=(11, 12, 13)):
    from megatron_llm_tpu.generation.engine import ContinuousBatchingEngine

    tok = ToyTokenizer()
    eng = ContinuousBatchingEngine(cfg, params, tok, max_slots=4,
                                   num_pages=64, page_size=16, mesh=mesh)
    reqs = [
        eng.submit(tok.tokenize(f"tensor parallel prompt {i}"), 8,
                   temperature=1.0, top_k=0, top_p=0.0, seed=s)
        for i, s in enumerate(seeds)
    ]
    eng.run_until_idle()
    return eng, [(r.result()[0], list(r.log_probs)) for r in reqs]


def test_engine_tp4_decode_parity(toy_model, eight_devices):
    cfg, params = toy_model
    eng1, base = _run_engine(cfg, params, None)
    mesh = ps.build_mesh(tensor_model_parallel_size=4,
                         data_parallel_size=1, devices=eight_devices[:4])
    eng4, tp = _run_engine(cfg, params, mesh)

    # pool really shards over the heads dim
    spec = eng4.pool.k.sharding.spec
    assert tuple(spec)[3] == ps.TP_AXIS, spec
    shard = eng4.pool.k.sharding.shard_shape(eng4.pool.k.shape)
    assert shard[3] == eng4.pool.k.shape[3] // 4
    # block tables stay host-side numpy
    assert isinstance(eng4._block_tables, np.ndarray)

    for (t0, l0), (t1, l1) in zip(base, tp):
        # tokens bitwise; log-probs within the row-parallel reduction bound
        assert t0 == t1
        np.testing.assert_allclose(l0, l1, atol=1e-5)


def test_engine_single_chip_mesh_degrades(toy_model):
    """mesh with tp=1: same tokens and log-probs as the no-mesh engine —
    the graceful single-chip degradation contract."""
    cfg, params = toy_model
    _, base = _run_engine(cfg, params, None)
    mesh = ps.build_mesh(devices=jax.devices()[:1])
    _, one = _run_engine(cfg, params, mesh)
    for (t0, l0), (t1, l1) in zip(base, one):
        assert t0 == t1
        assert l0 == l1  # bitwise: no collectives at tp=1


def test_engine_health_reports_mesh(toy_model, eight_devices):
    from megatron_llm_tpu.generation.server import MegatronServer

    cfg, params = toy_model
    mesh = ps.build_mesh(tensor_model_parallel_size=2,
                         data_parallel_size=1, devices=eight_devices[:2])
    from megatron_llm_tpu.generation.engine import ContinuousBatchingEngine

    eng = ContinuousBatchingEngine(cfg, params, ToyTokenizer(), max_slots=2,
                                   num_pages=32, page_size=16, mesh=mesh)
    srv = MegatronServer(eng)
    info = srv.health()
    assert info["tp"] == 2
    assert info["mesh"].get("tp") == 2

    from megatron_llm_tpu.observability.registry import get_registry

    text = get_registry().render()
    assert 'mlt_mesh_axis_size{axis="tp"} 2' in text


# ---------------------------------------------------------------------------
# linter: the 0.4.37 gap cannot regress in
# ---------------------------------------------------------------------------


def test_linter_forbids_direct_shard_map(tmp_path, capsys):
    from tools.linter import lint_file

    bad = tmp_path / "direct.py"
    bad.write_text("from jax import shard" + "_map\n")
    assert lint_file(str(bad)) == 1
    assert "compat" in capsys.readouterr().out

    bad2 = tmp_path / "direct2.py"
    bad2.write_text("fn = jax.shard" + "_map(f, mesh=m)\n")
    assert lint_file(str(bad2)) == 1

    bad3 = tmp_path / "direct3.py"
    bad3.write_text("from jax.experimental.shard" + "_map import shard"
                    + "_map\n")
    assert lint_file(str(bad3)) == 1

    # comments/docstring prose is allowed
    ok = tmp_path / "prose.py"
    ok.write_text("# jax.shard" + "_map is unavailable on 0.4.37\nx = 1\n")
    assert lint_file(str(ok)) == 0

    # compat.py itself is exempt
    compat_dir = tmp_path / "parallel"
    compat_dir.mkdir()
    exempt = compat_dir / "compat.py"
    exempt.write_text("from jax.experimental.shard"
                      "_map import shard_map\n")
    assert lint_file(str(exempt)) == 0


def test_repo_passes_shard_map_rule():
    import os

    from tools.linter import SHARD_MAP_RE, _is_compat, _strip_comment

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    offenders = []
    for sub in ("megatron_llm_tpu", "tools", "tests"):
        for dirpath, _dirs, files in os.walk(os.path.join(root, sub)):
            for name in files:
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                if _is_compat(path):
                    continue
                with open(path, encoding="utf-8", errors="replace") as f:
                    for i, line in enumerate(f, 1):
                        if SHARD_MAP_RE.search(_strip_comment(line)):
                            offenders.append(f"{path}:{i}")
    assert not offenders, offenders
