"""fp16 loss scaling, batch-size ramp-up, metrics registry, recompute parity.

Reference analogs: optimizer/grad_scaler.py semantics (growth/backoff/
hysteresis), megatron/microbatches.py calculators, megatron/metrics.py
registry, and activation recompute (core/tensor_parallel/random.py:175-245:
recompute must not change numerics).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from megatron_llm_tpu.models import init_model_params, make_config
from megatron_llm_tpu.optimizer.grad_scaler import (
    ScalerState,
    find_scaler_state,
    with_loss_scaling,
)


# ---------------------------------------------------------------------------
# Grad scaler unit tests
# ---------------------------------------------------------------------------


def _params():
    return {"w": jnp.ones((4,), jnp.float32)}


def test_scaler_skips_and_backs_off_on_overflow():
    opt = with_loss_scaling(
        optax.sgd(0.1), initial_scale=16.0, min_scale=1.0,
        hysteresis=2, growth_interval=100,
    )
    params = _params()
    state = opt.init(params)
    bad = {"w": jnp.full((4,), jnp.inf, jnp.float32)}

    # 1st overflow: hysteresis 2->1, no backoff yet, update zeroed
    updates, state = opt.update(bad, state, params)
    s = find_scaler_state(state)
    assert float(s.loss_scale) == 16.0
    assert int(s.hysteresis_left) == 1
    assert bool(s.last_skipped)
    assert np.all(np.asarray(updates["w"]) == 0.0)

    # 2nd overflow: hysteresis exhausted -> scale halves (tracker is NOT
    # replenished — only the growth branch resets it, reference
    # grad_scaler.py:88-106)
    updates, state = opt.update(bad, state, params)
    s = find_scaler_state(state)
    assert float(s.loss_scale) == 8.0
    assert int(s.hysteresis_left) == 0
    assert int(s.skipped_total) == 2

    # 3rd consecutive overflow: backs off again immediately
    updates, state = opt.update(bad, state, params)
    s = find_scaler_state(state)
    assert float(s.loss_scale) == 4.0

    # good step: applies the (unscaled) update
    good = {"w": jnp.full((4,), 4.0 * 2.0, jnp.float32)}  # scaled grads = 2
    updates, state = opt.update(good, state, params)
    s = find_scaler_state(state)
    assert not bool(s.last_skipped)
    np.testing.assert_allclose(np.asarray(updates["w"]), -0.1 * 2.0, rtol=1e-6)


def test_scaler_growth_after_interval():
    opt = with_loss_scaling(
        optax.sgd(0.1), initial_scale=4.0, growth_interval=3, hysteresis=1,
    )
    params = _params()
    state = opt.init(params)
    good = {"w": jnp.ones((4,), jnp.float32)}
    for _ in range(3):
        _, state = opt.update(good, state, params)
    s = find_scaler_state(state)
    assert float(s.loss_scale) == 8.0  # doubled after 3 finite steps
    assert int(s.growth_tracker) == 0


def test_scaler_inner_state_frozen_on_skip():
    opt = with_loss_scaling(optax.adam(0.1), initial_scale=2.0, hysteresis=1)
    params = _params()
    state = opt.init(params)
    good = {"w": jnp.ones((4,), jnp.float32)}
    _, state = opt.update(good, state, params)
    mu_before = np.asarray(jax.tree_util.tree_leaves(state[1])[1])
    bad = {"w": jnp.full((4,), jnp.nan, jnp.float32)}
    _, state = opt.update(bad, state, params)
    mu_after = np.asarray(jax.tree_util.tree_leaves(state[1])[1])
    np.testing.assert_array_equal(mu_before, mu_after)


def _tiny_cfg(**kw):
    defaults = dict(
        num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, vocab_size=256, seq_length=32,
        max_position_embeddings=64, params_dtype="float32",
        use_flash_attn=False,
    )
    defaults.update(kw)
    return make_config("llama2", **defaults)


def test_fp16_train_step_end_to_end():
    """fp16 + dynamic scaling: initial 2^32 scale overflows, backs off, and
    training proceeds with finite reported loss."""
    from megatron_llm_tpu.core.parallel_state import build_mesh, global_mesh
    from megatron_llm_tpu.training_step import make_jitted_train_step

    cfg = _tiny_cfg(params_dtype="float16")
    cfg.training.initial_loss_scale = 2.0 ** 20
    cfg.training.hysteresis = 1
    cfg.finalize(n_devices=1)
    mesh = build_mesh(devices=jax.devices()[:1])
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0, 256)
    batch = {
        "tokens": np.asarray(tok[:, :-1]),
        "labels": np.asarray(tok[:, 1:]),
        "loss_mask": np.ones((2, 32), np.float32),
    }
    with global_mesh(mesh):
        step, _o, sh = make_jitted_train_step(cfg, mesh, params)
        p = jax.device_put(params, sh["params"])
        o = jax.device_put(sh["opt_state_value"], sh["opt_state"])
        b = sh["place_batch"](batch)
        scales, losses = [], []
        for i in range(12):
            p, o, m = step(p, o, b, jnp.asarray(i))
            scales.append(float(m["loss_scale"]))
            losses.append(float(m["lm loss"]))
    # fp16 at 2^20 scale overflows at least once -> scale backed off
    assert min(scales) < 2.0 ** 20
    assert np.isfinite(losses[-1])
    # un-skipped steps actually train
    assert losses[-1] < losses[0]


# ---------------------------------------------------------------------------
# Microbatch calculators
# ---------------------------------------------------------------------------


def test_constant_calculator():
    from megatron_llm_tpu.microbatches import ConstantNumMicroBatches

    c = ConstantNumMicroBatches(16, 2, 2)
    assert c.get() == 4
    assert c.get_current_global_batch_size() == 16


def test_rampup_calculator_stages():
    from megatron_llm_tpu.microbatches import RampupBatchsizeNumMicroBatches

    # start 4, +4 per stage, over 80 samples, target 12: stages 4 -> 8 -> 12
    c = RampupBatchsizeNumMicroBatches(4, 4, 80, 12, 2, 2)
    assert c.get_current_global_batch_size() == 4
    assert c.get() == 1
    c.update(40)
    assert c.get_current_global_batch_size() == 8
    assert c.get() == 2
    c.update(80)
    assert c.get_current_global_batch_size() == 12
    c.update(10_000)
    assert c.get_current_global_batch_size() == 12
    assert c.get() == 3


def test_pretrain_with_rampup(tmp_path):
    """Driver integration: gbs ramps 4->8, consumed samples accounted."""
    from megatron_llm_tpu.data.indexed_dataset import make_builder
    from megatron_llm_tpu.training import pretrain

    prefix = str(tmp_path / "corpus_text_document")
    rng = np.random.RandomState(0)
    builder = make_builder(prefix + ".bin", vocab_size=250)
    for _ in range(80):
        builder.add_doc(rng.randint(1, 250, size=rng.randint(40, 100)))
    builder.finalize(prefix + ".idx")

    cfg = _tiny_cfg(vocab_size=256)
    cfg.data.seq_length = 32
    cfg.data.data_path = [prefix]
    cfg.data.tokenizer_type = "NullTokenizer"
    cfg.training.micro_batch_size = 4
    cfg.training.global_batch_size = 8
    cfg.training.rampup_batch_size = (4, 4, 12)  # 4 for 12 samples, then 8
    cfg.training.train_iters = 6
    cfg.training.eval_interval = 100
    cfg.logging.log_interval = 2
    cfg.finalize(n_devices=1)
    result = pretrain(cfg)
    assert result["iteration"] == 6
    # iterations 1-3 at gbs 4 (0,4,8 consumed), iteration 4+ at gbs 8
    assert result["consumed_samples"] == 4 * 3 + 8 * 3
    assert np.isfinite(float(result["last_metrics"]["lm loss"]))


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------


def test_metrics_registry_values():
    from megatron_llm_tpu.metrics import MetricInput, compute_metrics

    batch = {
        "labels": jnp.asarray([[1, 2, 3, 4]]),
        "loss_mask": jnp.asarray([[1.0, 1.0, 0.0, 1.0]]),
    }
    logits = jnp.full((1, 4, 8), -10.0)
    # argmax correct at positions 0 and 3, wrong at 1 (pos 2 is masked out)
    logits = logits.at[0, 0, 1].set(10.0)
    logits = logits.at[0, 1, 7].set(10.0)
    logits = logits.at[0, 2, 3].set(10.0)
    logits = logits.at[0, 3, 4].set(10.0)
    per_token = jnp.asarray([[0.5, 1.0, 99.0, 0.25]])
    inp = MetricInput(batch=batch, per_token_loss=per_token, logits=logits)
    out = compute_metrics(["ppl", "accuracy", "count"], inp)
    np.testing.assert_allclose(
        float(out["ppl"]), np.exp((0.5 + 1.0 + 0.25) / 3), rtol=1e-6
    )
    np.testing.assert_allclose(float(out["accuracy"]), 2.0 / 3.0, rtol=1e-6)
    np.testing.assert_allclose(float(out["count"]), 3.0, rtol=1e-6)


def test_eval_step_with_metrics():
    from megatron_llm_tpu.core.parallel_state import build_mesh, global_mesh
    from megatron_llm_tpu.training import make_eval_step

    cfg = _tiny_cfg()
    cfg.logging.metrics = ["ppl", "accuracy"]
    cfg.finalize(n_devices=1)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0, 256)
    batch = {
        "tokens": tok[:, :-1],
        "labels": tok[:, 1:],
        "loss_mask": jnp.ones((2, 32), jnp.float32),
    }
    with global_mesh(build_mesh(devices=jax.devices()[:1])):
        eval_step = make_eval_step(cfg)
        m = eval_step(params, batch)
    assert set(m) >= {"lm loss", "ppl", "accuracy"}
    np.testing.assert_allclose(
        float(m["ppl"]), np.exp(float(m["lm loss"])), rtol=1e-5
    )
    assert 0.0 <= float(m["accuracy"]) <= 1.0


# ---------------------------------------------------------------------------
# Activation recompute parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("granularity", ["full", "selective"])
def test_recompute_grads_match_no_recompute(granularity):
    from megatron_llm_tpu.models.language_model import loss_from_batch

    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0, 256)
    batch = {
        "tokens": tok[:, :-1],
        "labels": tok[:, 1:],
        "loss_mask": jnp.ones((2, 32), jnp.float32),
    }

    def grads_for(gran):
        cfg = _tiny_cfg()
        cfg.parallel.recompute_granularity = gran
        cfg.finalize(n_devices=1)
        params = init_model_params(cfg, jax.random.PRNGKey(0))
        return jax.grad(lambda p: loss_from_batch(cfg, p, batch)[0])(params)

    g_ref = grads_for(None)
    g_remat = grads_for(granularity)
    for a, b in zip(jax.tree_util.tree_leaves(g_ref),
                    jax.tree_util.tree_leaves(g_remat)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)
