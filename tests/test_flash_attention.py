"""Flash attention kernel numerics vs the exact XLA attention
(reference analog: fused_kernels/tests/test_fused_kernels.py — fused kernels
vs unfused within tolerance). Runs in pallas interpret mode on CPU."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.ops.attention import make_attention_bias, xla_attention
from megatron_llm_tpu.ops.pallas.flash_attention import flash_attention


def _rand_qkv(key, b=1, s=256, n=4, nkv=2, d=128, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, n, d), dtype)
    k = jax.random.normal(kk, (b, s, nkv, d), dtype)
    v = jax.random.normal(kv, (b, s, nkv, d), dtype)
    return q, k, v


def _ref(q, k, v, sliding_window=None, segment_ids=None, causal=True):
    bias = make_attention_bias(
        q.shape[1], k.shape[1], causal=causal, sliding_window=sliding_window,
        segment_ids_q=segment_ids, segment_ids_kv=segment_ids,
    )
    return xla_attention(q, k, v, bias=bias)


@pytest.mark.parametrize("nkv", [4, 2, 1])
def test_fwd_matches_reference(nkv):
    q, k, v = _rand_qkv(jax.random.PRNGKey(0), nkv=nkv)
    out = flash_attention(q, k, v, block_q=128, block_kv=128, interpret=True)
    ref = _ref(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_fwd_sliding_window():
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), s=256)
    out = flash_attention(q, k, v, sliding_window=64, block_q=64, block_kv=64,
                          interpret=True)
    ref = _ref(q, k, v, sliding_window=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_fwd_segment_ids():
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), s=128)
    seg = jnp.concatenate(
        [jnp.zeros((1, 64), jnp.int32), jnp.ones((1, 64), jnp.int32)], axis=1
    )
    out = flash_attention(q, k, v, segment_ids=seg, block_q=64, block_kv=64,
                          interpret=True)
    ref = _ref(q, k, v, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("sliding_window", [None, 96])
def test_grads_match_reference(sliding_window):
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), s=256, n=4, nkv=2)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, sliding_window=sliding_window,
                            block_q=64, block_kv=64, interpret=True) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(_ref(q, k, v, sliding_window=sliding_window) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4,
            err_msg=f"d{name} mismatch",
        )


def test_grads_segment_ids():
    q, k, v = _rand_qkv(jax.random.PRNGKey(4), s=128, n=2, nkv=2, d=128)
    seg = jnp.concatenate(
        [jnp.zeros((1, 48), jnp.int32), jnp.ones((1, 80), jnp.int32)], axis=1
    )

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, segment_ids=seg, block_q=64,
                                       block_kv=64, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref(q, k, v, segment_ids=seg) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4)


def test_bf16_fwd_close():
    q, k, v = _rand_qkv(jax.random.PRNGKey(5), dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, block_q=128, block_kv=128, interpret=True)
    ref = _ref(q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32))
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2, rtol=3e-2
    )


# ---------------------------------------------------------------------------
# bidirectional (causal=False) — the BERT / T5-encoder path
# ---------------------------------------------------------------------------


def _ref_bidir(q, k, v, segment_ids=None):
    return _ref(q, k, v, segment_ids=segment_ids, causal=False)


def test_fwd_bidirectional_matches_reference():
    q, k, v = _rand_qkv(jax.random.PRNGKey(5))
    out = flash_attention(q, k, v, causal=False, block_q=128, block_kv=128,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref_bidir(q, k, v)),
                               rtol=2e-5, atol=2e-5)


def test_fwd_bidirectional_segment_ids():
    """Non-causal + segment gating: the pipelined-BERT padding formulation."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(6), s=256)
    seg = (jnp.arange(256)[None, :] >= 200).astype(jnp.int32)  # pads seg 1
    out = flash_attention(q, k, v, causal=False, segment_ids=seg,
                          block_q=64, block_kv=64, interpret=True)
    ref = _ref_bidir(q, k, v, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_grads_bidirectional_match_reference():
    q, k, v = _rand_qkv(jax.random.PRNGKey(7), s=128, d=64)

    def loss_flash(q_, k_, v_):
        return jnp.sum(flash_attention(q_, k_, v_, causal=False, block_q=64,
                                       block_kv=64, interpret=True) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(_ref_bidir(q_, k_, v_) ** 2)

    g1 = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_grads_bidirectional_segment_ids():
    """Backward under the exact pipelined-BERT/T5-encoder training config:
    non-causal attention with pads expressed as segment ids."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(8), s=128, d=64)
    seg = (jnp.arange(128)[None, :] >= 100).astype(jnp.int32)

    def loss_flash(q_, k_, v_):
        return jnp.sum(flash_attention(q_, k_, v_, causal=False,
                                       segment_ids=seg, block_q=64,
                                       block_kv=64, interpret=True) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(_ref_bidir(q_, k_, v_, segment_ids=seg) ** 2)

    g1 = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_env_block_override(monkeypatch):
    """MLT_FLASH_BLOCK_Q/KV (tools/mfu_sweep.py retune rows): applied when
    it divides the call's seq, is a 128-lane-tile multiple, and respects
    the VMEM cap (ADVICE r4 #2); ignored with a note otherwise; numerics
    unchanged either way."""
    from megatron_llm_tpu.ops.pallas import flash_attention as fa

    q, k, v = _rand_qkv(jax.random.PRNGKey(9), s=256, d=64)
    base = flash_attention(q, k, v, interpret=True)

    monkeypatch.setenv("MLT_FLASH_BLOCK_Q", "128")
    monkeypatch.setenv("MLT_FLASH_BLOCK_KV", "128")
    assert fa._env_block("MLT_FLASH_BLOCK_Q", 256) == 128
    out = flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base),
                               atol=2e-5, rtol=2e-5)

    monkeypatch.setenv("MLT_FLASH_BLOCK_Q", "100")  # does not divide 256
    assert fa._env_block("MLT_FLASH_BLOCK_Q", 256) is None
    # ADVICE r4 #2: a divisor that is NOT a 128-multiple (passes the old
    # check, dies as an opaque Mosaic/VMEM error later) is now rejected...
    monkeypatch.setenv("MLT_FLASH_BLOCK_Q", "64")
    assert fa._env_block("MLT_FLASH_BLOCK_Q", 256) is None
    # ...as is one above the VMEM cap the caller would auto-pick under
    monkeypatch.setenv("MLT_FLASH_BLOCK_Q", "1024")
    assert fa._env_block("MLT_FLASH_BLOCK_Q", 2048, cap=512) is None
    out2 = flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(base),
                               atol=2e-5, rtol=2e-5)
