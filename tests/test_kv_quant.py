"""Quantized paged KV cache + quantized DP collectives (ISSUE 13).

Contracts pinned here:

* **bf16 untouched**: ``kv_dtype="bf16"`` engines produce plain arrays and
  the same compiled-program keys shape as before (the existing parity
  suites — test_paged_engine / test_prefix_cache / test_speculative /
  test_ragged_tick — are the real bitwise gate; this file covers the new
  modes).
* **analytic error bounds** (ops/kv_quant.py module docstring): one-shot
  page quantization ``|x - q*s| <= s/2``; decode appends that grow the
  page scale re-round once more, ``<= s_final`` (2x the one-shot bound).
* **collision-safe writes**: consecutive rows of one chunk / verify block
  share a page; every token must survive the page-granular update.
* **accuracy gates** (documented in docs/guide/quantization.md): greedy
  tokens match bf16 on the short-horizon sanity workload; per-token
  log-prob deltas stay under ``LOGPROB_GATE`` on the long horizon — across
  prefix-cache on/off, speculative on/off, preempt/resume, and tp=4.
* **compiled-program fingerprints**: an int8 engine must never reuse a
  bf16 executable — the kv mode + scale dtype are part of every cache key.
* **quantized DP all-reduce** (parallel/quantized.py): elementwise error
  within the chunk-scale bound, exact for small leaves, and a loss-delta
  gate vs the bf16-sync baseline (``QDP_LOSS_GATE``) on the CPU-sanity
  pretrain shape at dp=2 — flag off by default.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.core.parallel_state import build_mesh, global_mesh
from megatron_llm_tpu.generation import generation as gen
from megatron_llm_tpu.generation.engine import ContinuousBatchingEngine
from megatron_llm_tpu.models import init_model_params, make_config
from megatron_llm_tpu.ops import kv_quant

# accuracy gates, measured on the CPU-sanity shapes below and documented
# in docs/guide/quantization.md ("Accuracy gates"): greedy agreement is
# asserted exactly on the short horizon; log-prob deltas on the long
# horizon measured ~3e-4 (int8) — gated at 10x margin
LOGPROB_GATE = 5e-3
# dp=2 quantized-vs-bf16 sync loss delta measured ~1.5e-4 over 8 steps —
# gated at >10x margin
QDP_LOSS_GATE = 2e-3

GREEDY = dict(top_k=1, termination_id=0, use_eod_for_termination=False)

CFG_KW = dict(hidden_size=64, num_attention_heads=4,
              num_attention_heads_kv=4, ffn_hidden_size=128, vocab_size=512,
              seq_length=256, max_position_embeddings=256,
              params_dtype="float32", micro_batch_size=1,
              global_batch_size=1, train_iters=1)


@pytest.fixture(scope="module")
def models():
    from megatron_llm_tpu.generation import DraftModel
    from megatron_llm_tpu.generation.speculative import (
        extend_params_identity,
    )

    cfg = make_config("llama2", num_layers=2, **CFG_KW)
    dcfg = make_config("llama2", num_layers=1, **CFG_KW)
    dparams = init_model_params(dcfg, jax.random.PRNGKey(1))
    params = extend_params_identity(dcfg, dparams, cfg, jax.random.PRNGKey(0))
    return {"cfg": cfg, "params": params,
            "draft": DraftModel(dcfg, dparams)}


def _prompts(n, length, seed=0, vocab=512):
    rng = np.random.default_rng(seed)
    return [[int(t) for t in rng.integers(1, vocab, length)]
            for _ in range(n)]


def _engine(models, kv_dtype, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_seq", 128)
    return ContinuousBatchingEngine(models["cfg"], models["params"],
                                    kv_dtype=kv_dtype, **kw)


def _decode(eng, prompts, gen_len=12, **kw):
    reqs = [eng.submit(p, gen_len, **{**GREEDY, **kw}) for p in prompts]
    eng.run_until_idle()
    return [r.result(timeout=120) for r in reqs]


# ---------------------------------------------------------------------------
# ops/kv_quant.py unit contracts
# ---------------------------------------------------------------------------


def test_one_shot_page_quant_error_bound():
    """Whole-page quantization error <= scale/2 per element — the
    int8_quant_error_bound-style analytic bound, both storage dtypes."""
    rng = np.random.default_rng(0)
    vals = jnp.asarray(rng.normal(0, 3.0, (5, 16, 4, 8)).astype(np.float32))
    for kv_dtype in ("int8", "fp8"):
        qp = kv_quant.quantize_pages(vals, kv_dtype)
        back = kv_quant.dequantize_pages(qp, jnp.float32)
        err = np.abs(np.asarray(back) - np.asarray(vals))
        # per-(page, head) bound: scale/2
        bound = np.asarray(qp.scale)[:, None, :, None] / 2.0
        if kv_dtype == "fp8":
            # fp8 rounding is relative (RNE at ~2^-3 of magnitude), not
            # the uniform int8 grid — bound by the format's worst-case
            # relative step instead
            bound = np.maximum(bound, np.abs(np.asarray(vals)) * 2 ** -3)
        assert (err <= bound + 1e-7).all(), kv_dtype
        assert float(jnp.max(jnp.abs(back))) <= float(
            jnp.max(jnp.abs(vals))) * 1.01


def test_append_requant_error_bound():
    """Token-by-token appends with growing magnitudes: each earlier token
    is re-rounded every time the page scale GROWS, adding <= s_new/2 per
    growth — the documented per-page append bound is the running sum
    ``s_at_write/2 + sum(s_g/2 over later growths)`` (ops/kv_quant.py
    module docstring), tracked here against the actual scale history."""
    rng = np.random.default_rng(1)
    page, nkv, d = 16, 4, 8
    pool = kv_quant.make_pool((2, page, nkv, d), "int8", jnp.float32)
    # magnitudes ramp 1x -> 4x so the page scale grows on most appends
    toks = [rng.normal(0, 1.0 + 3.0 * i / (page - 1), (nkv, d))
            .astype(np.float32) for i in range(page)]
    bounds = np.zeros((page, nkv), np.float64)
    prev_scale = np.zeros((nkv,), np.float64)
    for off, t in enumerate(toks):
        pool = kv_quant.paged_write(
            pool, jnp.asarray([[1]], jnp.int32), jnp.asarray([[off]]),
            jnp.asarray(t)[None, None])
        s = np.asarray(pool.scale[1], np.float64)
        bounds[off] = s / 2.0  # this token's own rounding
        grew = s > prev_scale + 1e-12
        # every EARLIER token re-rounds under the grown scale
        bounds[:off][:, grew] += s[grew] / 2.0
        prev_scale = s
    back = np.asarray(kv_quant.dequantize_pages(
        kv_quant.QuantPagedKV(pool.q[1], pool.scale[1]), jnp.float32))
    vals = np.stack(toks)
    err = np.abs(back - vals)
    assert (err <= bounds[:, :, None] + 1e-7).all()
    # and in PRACTICE the random-walk accumulation stays near the
    # one-shot figure: well under 2x s_final (the rule-of-thumb
    # docs/guide/quantization.md quotes)
    s_final = np.asarray(pool.scale[1])
    assert float(err.max()) < 2.0 * float(s_final.max())


def test_collision_safe_chunk_write():
    """A whole chunk's rows target the same pages (the ragged/prefill
    shape): every token must survive the collision-safe 3-phase update,
    within the one-shot bound (all rows fresh-quantize together)."""
    rng = np.random.default_rng(2)
    page, nkv, d = 16, 4, 8
    pool = kv_quant.make_pool((4, page, nkv, d), "int8", jnp.float32)
    # 32 rows = pages 1..2 fully written in ONE call, offs 0..15 each
    vals = rng.normal(0, 2.0, (1, 32, nkv, d)).astype(np.float32)
    page_ids = np.repeat([1, 2], 16)[None]
    offs = np.tile(np.arange(16), 2)[None]
    out = kv_quant.paged_write(pool, jnp.asarray(page_ids),
                               jnp.asarray(offs), jnp.asarray(vals))
    for pid, lo in ((1, 0), (2, 16)):
        back = np.asarray(kv_quant.dequantize_pages(
            kv_quant.QuantPagedKV(out.q[pid], out.scale[pid]), jnp.float32))
        want = vals[0, lo:lo + 16]
        bound = np.asarray(out.scale[pid])[None, :, None] / 2.0
        assert (np.abs(back - want) <= bound + 1e-7).all()


def test_fresh_page_resets_stale_scale():
    """A freed page's stale (huge) scale must not poison the next tenant:
    an ``offs == 0`` write resets the page scale to the new content."""
    page, nkv, d = 16, 4, 8
    pool = kv_quant.make_pool((3, page, nkv, d), "int8", jnp.float32)
    big = jnp.full((1, 1, nkv, d), 1000.0)
    pool = kv_quant.paged_write(pool, jnp.asarray([[2]]),
                                jnp.asarray([[0]]), big)
    assert float(pool.scale[2].max()) > 1.0
    small = jnp.full((1, 1, nkv, d), 0.5)
    pool = kv_quant.paged_write(pool, jnp.asarray([[2]]),
                                jnp.asarray([[0]]), small)
    # scale reset: 0.5/127, not inherited from the 1000.0 tenant
    assert float(pool.scale[2].max()) < 0.01
    back = kv_quant.dequantize_pages(
        kv_quant.QuantPagedKV(pool.q[2], pool.scale[2]), jnp.float32)
    assert abs(float(back[0, 0, 0]) - 0.5) < 0.01


def test_mid_page_append_preserves_prefix():
    """An ``offs > 0`` append keeps earlier tokens in the page (requant
    merge), unlike the fresh-reset path."""
    page, nkv, d = 16, 4, 8
    pool = kv_quant.make_pool((3, page, nkv, d), "int8", jnp.float32)
    first = jnp.full((1, 1, nkv, d), 2.0)
    pool = kv_quant.paged_write(pool, jnp.asarray([[1]]),
                                jnp.asarray([[0]]), first)
    second = jnp.full((1, 1, nkv, d), 4.0)
    pool = kv_quant.paged_write(pool, jnp.asarray([[1]]),
                                jnp.asarray([[1]]), second)
    back = np.asarray(kv_quant.dequantize_pages(
        kv_quant.QuantPagedKV(pool.q[1], pool.scale[1]), jnp.float32))
    s = float(pool.scale[1].max())
    assert abs(back[0, 0, 0] - 2.0) <= s  # re-rounded once: 2x bound
    assert abs(back[1, 0, 0] - 4.0) <= s / 2 + 1e-7


def test_bf16_pool_is_plain_array():
    """The default mode never builds a container — the bitwise contract's
    structural half (the parity suites are the behavioral half)."""
    pool = kv_quant.make_pool((2, 4, 16, 4, 8), "bf16", jnp.float32)
    assert not kv_quant.is_quantized(pool)
    assert kv_quant.scale_nbytes(pool) == 0
    q = kv_quant.make_pool((2, 4, 16, 4, 8), "int8", jnp.float32)
    assert kv_quant.is_quantized(q)
    assert q.q.dtype == jnp.int8 and q.scale.shape == (2, 4, 4)
    # int8 value storage is 1/4 the fp32 pool bytes (1/2 of bf16)
    assert kv_quant.pool_nbytes(q) * 4 == kv_quant.pool_nbytes(pool)


# ---------------------------------------------------------------------------
# engine accuracy gates
# ---------------------------------------------------------------------------


def test_greedy_agreement_short_horizon(models):
    """int8 AND fp8 greedy tokens match bf16 exactly on the sanity
    workload (short horizon, cache on)."""
    prompts = _prompts(3, 37)
    base = _decode(_engine(models, "bf16"), prompts)
    for kv_dtype in ("int8", "fp8"):
        got = _decode(_engine(models, kv_dtype), prompts)
        for (tb, _), (tq, _) in zip(base, got):
            assert tb == tq, kv_dtype


def test_logprob_delta_long_horizon(models):
    """Per-token log-prob delta vs bf16 stays under LOGPROB_GATE over a
    long decode (the documented int8 accuracy gate)."""
    prompts = _prompts(2, 33, seed=3)
    base = _decode(_engine(models, "bf16"), prompts, gen_len=64)
    got = _decode(_engine(models, "int8"), prompts, gen_len=64)
    for (tb, lb), (tq, lq) in zip(base, got):
        assert tb == tq
        delta = max(abs(a - b) for a, b in zip(lb, lq))
        assert delta < LOGPROB_GATE, delta


def test_cache_on_off_agreement_int8(models):
    """Prefix-cache hits replay quantized pages + scales: warm-cache
    decode tokens and log-probs equal the cold decode (deterministic
    quantization makes this exact at int8 too)."""
    shared = _prompts(1, 48, seed=4)[0]
    tails = _prompts(2, 6, seed=5)
    warm = _engine(models, "int8")
    _decode(warm, [shared + tails[0]], gen_len=8)
    h0 = warm.prefix_hit_tokens
    warm_out = _decode(warm, [shared + tails[1]], gen_len=8)
    assert warm.prefix_hit_tokens - h0 >= 48 // warm.page_size * \
        warm.page_size  # pages actually reused
    cold = _engine(models, "int8")
    cold_out = _decode(cold, [shared + tails[1]], gen_len=8)
    assert warm_out[0][0] == cold_out[0][0]
    assert warm_out[0][1] == cold_out[0][1]
    nocache = _engine(models, "int8", prefix_cache=False)
    nc_out = _decode(nocache, [shared + tails[1]], gen_len=8)
    assert nc_out[0][0] == cold_out[0][0]


def test_speculative_agreement_int8(models):
    """Speculation at int8: spec-on tokens equal spec-off tokens on the
    sanity workload, and the identity-extended draft still accepts
    everything (both models read the same quantized page discipline)."""
    prompts = _prompts(3, 37)
    plain = _decode(_engine(models, "int8"), prompts)
    eng = _engine(models, "int8", spec_k=2, spec_draft=models["draft"])
    spec = _decode(eng, prompts)
    for (tp_, _), (ts, _) in zip(plain, spec):
        assert tp_ == ts
    assert eng.spec_draft_tokens > 0
    assert eng.spec_accepted_tokens == eng.spec_draft_tokens


def test_preempt_resume_agreement_int8(models):
    """Preemption parks quantized pages (values + scales) in the trie;
    resume matches them back and continues — tokens equal the
    uninterrupted run."""
    prompt = _prompts(1, 37)[0]
    eng = _engine(models, "int8", max_slots=2)
    req = eng.submit(prompt, 16, **GREEDY)
    for _ in range(8):
        eng.step()
    assert eng.preempt(req)
    eng.run_until_idle()
    got = req.result(timeout=120)
    want = _decode(_engine(models, "int8", max_slots=2), [prompt],
                   gen_len=16)[0]
    assert got[0] == want[0]


def test_tp4_agreement_int8(models):
    """tp=4 int8 engine: pool + scales shard over the heads dim; tokens
    equal the single-chip int8 engine."""
    prompts = _prompts(2, 37)
    single = _decode(_engine(models, "int8", max_slots=2), prompts,
                     gen_len=10)
    mesh = build_mesh(tensor_model_parallel_size=4,
                      devices=jax.devices()[:4])
    with global_mesh(mesh):
        eng = _engine(models, "int8", max_slots=2, mesh=mesh)
        assert eng.pool.k.q.sharding.spec[3] == "tp"
        assert eng.pool.k.scale.sharding.spec[2] == "tp"
        sharded = _decode(eng, prompts, gen_len=10)
    for (ts, _), (tm, _) in zip(single, sharded):
        assert ts == tm


def test_legacy_split_dispatch_int8(models):
    """The non-ragged (legacy split) tick and the monolithic prefill path
    also run quantized: ragged-off agrees with ragged-on, and
    prefill_chunk=0 (monolithic, cache off) still matches bf16 greedy."""
    prompts = _prompts(2, 37)
    ragged = _decode(_engine(models, "int8"), prompts)
    legacy = _decode(_engine(models, "int8", ragged=False), prompts)
    for (tr, lr), (tl, ll) in zip(ragged, legacy):
        assert tr == tl
    mono16 = _decode(_engine(models, "bf16", prefill_chunk=0), prompts)
    mono8 = _decode(_engine(models, "int8", prefill_chunk=0), prompts)
    for (tb, _), (tq, _) in zip(mono16, mono8):
        assert tb == tq


# ---------------------------------------------------------------------------
# compiled-program fingerprints + telemetry
# ---------------------------------------------------------------------------


def test_kv_dtype_flips_compiled_program_keys(models):
    """Flipping --kv_dtype must produce DISTINCT cached_jit keys for the
    tick (an int8 engine reusing a bf16 executable would read int8 bytes
    as bf16) — the kv mode + storage/scale dtypes live in every key."""
    e16 = _engine(models, "bf16")
    e8 = _engine(models, "int8")
    assert e16.pool.kv_statics != e8.pool.kv_statics
    assert "int8" in str(e8.pool.kv_statics)
    assert e8.pool.kv_statics[-1] == "float32"  # scale dtype folded in
    before = set(gen._JIT_CACHE)
    f16 = e16._ragged_tick(0)
    f8 = e8._ragged_tick(0)
    assert f16 is not f8
    new_keys = [k for k in gen._JIT_CACHE if k not in before]
    tick_keys = [k for k in set(gen._JIT_CACHE)
                 if k[1] == "engine_ragged_tick"]
    kv_entries = {k: [t for t in k[2] if isinstance(t, tuple)
                      and t and t[0] == "kv"] for k in tick_keys}
    assert all(v for v in kv_entries.values()), (
        "every tick key must carry the kv statics tuple")
    del new_keys


def test_kv_metrics_and_health(models):
    """/metrics gains mlt_engine_kv_pool_bytes / kv_scale_bytes /
    kv_dtype info; /health carries kv_dtype + byte budget; the router's
    ReplicaView parses them (capacity-aware routing input)."""
    from megatron_llm_tpu.generation.server import MegatronServer
    from megatron_llm_tpu.observability import registry as obs_registry
    from megatron_llm_tpu.serving.router.registry import ReplicaView

    eng = _engine(models, "int8")
    srv = MegatronServer(eng)
    health = srv.health()
    assert health["kv_dtype"] == "int8"
    assert health["kv_pool_bytes"] == eng.pool.kv_pool_bytes() > 0
    assert health["kv_scale_bytes"] == eng.pool.kv_scale_bytes() > 0
    text = srv.metrics_text()
    assert "mlt_engine_kv_pool_bytes" in text
    assert "mlt_engine_kv_scale_bytes" in text
    assert 'mlt_engine_kv_dtype_info{kv_dtype="int8"}' in text
    view = ReplicaView.parse("http://x", health)
    assert view.kv_dtype == "int8"
    assert view.kv_pool_bytes == eng.pool.kv_pool_bytes()
    assert view.free_kv_bytes is not None and view.free_kv_bytes > 0
    # pre-ISSUE-13 replicas keep conservative defaults
    old = ReplicaView.parse("http://y", {"status": "ok"})
    assert old.kv_dtype == "bf16" and old.free_kv_bytes is None
    del obs_registry


def test_int8_pool_bytes_half_of_bf16():
    """The capacity lever itself: at equal page counts an int8 pool's
    value bytes are half a bf16 pool's (quarter of this fp32-on-CPU
    suite's), so a fixed byte budget carries ~2x the pages (modulo the
    reported scale overhead)."""
    cfg = make_config("llama2", num_layers=2, **{**CFG_KW,
                                                 "params_dtype": "bfloat16"})
    from megatron_llm_tpu.generation.engine import PagedKVPool

    p16 = PagedKVPool(cfg, 33, 16)
    p8 = PagedKVPool(cfg, 33, 16, kv_dtype="int8")
    assert p8.kv_pool_bytes() * 2 == p16.kv_pool_bytes()
    assert p16.kv_scale_bytes() == 0
    # scale overhead: one f32 per (layer, page, head) per cache — small
    # relative to page payload (page_size * d elements)
    assert p8.kv_scale_bytes() < p8.kv_pool_bytes() / 16


def test_lock_rule_covers_peak_active_slots():
    """Anti-vacuity (the ISSUE 10 idiom): the new capacity-telemetry
    field really is in the graftcheck lock model for the engine — the
    repo sweep's cleanliness over engine.py covers it, not vacuously."""
    import ast as ast_mod
    import os

    from tools.graftcheck import core
    from tools.graftcheck.rules.locks import LockDisciplineRule

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "megatron_llm_tpu", "generation",
                        "engine.py")
    ctx = core.FileContext(path)
    rule = LockDisciplineRule()
    for node in ast_mod.walk(ctx.tree):
        if isinstance(node, ast_mod.ClassDef) \
                and node.name == "ContinuousBatchingEngine":
            model = rule._build(ctx, node)
            assert model is not None
            assert "peak_active_slots" in model.guards
            assert model.guards["peak_active_slots"] == {"_lock"}
            break
    else:
        raise AssertionError("engine class not found")


def test_peak_active_slots_on_health(models):
    """The capacity bench's headline number is first-class telemetry:
    /health carries the engine's concurrent-decode high-water mark."""
    from megatron_llm_tpu.generation.server import MegatronServer

    eng = _engine(models, "int8")
    _decode(eng, _prompts(3, 37), gen_len=6)
    assert eng.peak_active_slots >= 3
    assert MegatronServer(eng).health()["peak_active_slots"] == \
        eng.peak_active_slots


def test_kv_dtype_flag_flows_from_config(models):
    """cfg.inference.kv_dtype drives the engine default (the --kv_dtype
    flag path), and bad values fail loudly."""
    import dataclasses

    cfg = dataclasses.replace(models["cfg"])
    cfg.inference = dataclasses.replace(cfg.inference, kv_dtype="int8")
    eng = ContinuousBatchingEngine(cfg, models["params"], max_slots=2,
                                   max_seq=128)
    assert eng.kv_dtype == "int8"
    assert kv_quant.is_quantized(eng.pool.k)
    with pytest.raises(AssertionError):
        _engine(models, "int4")


# ---------------------------------------------------------------------------
# quantized DP gradient all-reduce (parallel/quantized.py)
# ---------------------------------------------------------------------------


def _qdp_mesh(n=2):
    return build_mesh(data_parallel_size=n, devices=jax.devices()[:n])


def test_quantized_allreduce_unit_bound():
    """Elementwise: quantized dp-mean within the per-chunk scale bound of
    the exact mean; small leaves exact (pmean path)."""
    from jax.sharding import PartitionSpec as P

    from megatron_llm_tpu.parallel import compat
    from megatron_llm_tpu.parallel.quantized import (
        quantized_allreduce_mean,
    )

    mesh = _qdp_mesh(4)
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1.0, (4, 8192)).astype(np.float32)
    small = rng.normal(0, 1.0, (4, 64)).astype(np.float32)

    def body(xl, sl):
        return (quantized_allreduce_mean(xl[0], "dp", 4),
                quantized_allreduce_mean(sl[0], "dp", 4))

    f = jax.jit(compat.shard_map(
        body, mesh=mesh, in_specs=(P("dp"), P("dp")),
        out_specs=(P(), P()), axis_names=set(mesh.axis_names),
        check_vma=False))
    got, got_small = f(jnp.asarray(x), jnp.asarray(small))
    ref = x.mean(0)
    # bound: one sender-side + one result-side rounding per element
    s_in = np.abs(x).reshape(4, 4, -1).max(axis=2) / 127.0
    bound = s_in.max() / 2.0 + np.abs(ref).max() / 127.0 / 2.0 + 1e-6
    assert np.max(np.abs(np.asarray(got) - ref)) <= bound * 2
    # small leaves: exact pmean
    np.testing.assert_allclose(np.asarray(got_small), small.mean(0),
                               rtol=1e-6)


def _pretrain_losses(quantized: bool, steps: int = 8):
    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=4, ffn_hidden_size=128, vocab_size=512,
        seq_length=64, max_position_embeddings=64, params_dtype="float32",
        micro_batch_size=2, global_batch_size=8, train_iters=steps,
        lr=1e-3, quantized_grad_allreduce=quantized)
    cfg.parallel.data_parallel_size = 2
    from megatron_llm_tpu.training_step import make_jitted_train_step

    mesh = _qdp_mesh(2)
    with global_mesh(mesh):
        params = init_model_params(cfg, jax.random.PRNGKey(0))
        step, _, sh = make_jitted_train_step(cfg, mesh, params)
        opt_state = sh["opt_state_value"]
        rng = np.random.default_rng(0)
        losses = []
        for it in range(steps):
            tokens = rng.integers(1, 512, (8, 64)).astype(np.int32)
            batch = sh["place_batch"](
                {"tokens": tokens, "labels": tokens,
                 "loss_mask": np.ones((8, 64), np.float32)})
            params, opt_state, mets = step(params, opt_state, batch,
                                           jnp.int32(it))
            losses.append(float(mets["lm loss"]))
    return losses


def test_quantized_dp_loss_trajectory_gate():
    """THE acceptance gate: the CPU-sanity pretrain loss trajectory under
    --quantized_grad_allreduce stays within QDP_LOSS_GATE (relative) of
    the bf16-sync baseline at dp=2, microbatch accumulation included
    (gbs 8 = mbs 2 x dp 2 x num_micro 2)."""
    base = _pretrain_losses(False)
    quant = _pretrain_losses(True)
    # step-0 forward differs only by reduction order (dp-mean of local
    # means vs one global mean) — float-noise, not quantization
    assert abs(base[0] - quant[0]) < 1e-5
    rel = max(abs(a - b) / max(abs(a), 1e-9) for a, b in zip(base, quant))
    assert rel < QDP_LOSS_GATE, (rel, base, quant)
    # and both actually trained
    assert base[-1] < base[0] and quant[-1] < quant[0]


def test_quantized_dp_off_by_default_and_scoped():
    """Flag default False; unsupported meshes are refused loudly."""
    from megatron_llm_tpu.parallel.quantized import (
        make_quantized_dp_grad_fn,
        quantized_dp_supported,
    )

    cfg = make_config("llama2", num_layers=2, **CFG_KW)
    assert cfg.training.quantized_grad_allreduce is False
    assert not quantized_dp_supported(cfg, None)
    mesh1 = build_mesh(devices=jax.devices()[:1])
    assert not quantized_dp_supported(cfg, mesh1)
    mesh_tp = build_mesh(tensor_model_parallel_size=2,
                         data_parallel_size=2, devices=jax.devices()[:4])
    assert not quantized_dp_supported(cfg, mesh_tp)
    with pytest.raises(AssertionError):
        make_quantized_dp_grad_fn(cfg, mesh_tp, None, 1)
