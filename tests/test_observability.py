"""Observability subsystem (ISSUE 4): span tracer ring/nesting + Chrome
trace validity, Prometheus registry (escaping, types, concurrency),
exporter endpoint + on-demand profiler trigger, flops accounting vs a
hand-counted config, the no-device-sync lint rule, watchdog trace dumps,
and the driver integration (trace phases present, /metrics fields on
pretrain and the generation server, bitwise loss parity on/off)."""

import json
import os
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_llm_tpu.observability import flops as flops_mod
from megatron_llm_tpu.observability import registry as registry_mod
from megatron_llm_tpu.observability import trace as trace_mod
from megatron_llm_tpu.observability.exporter import MetricsExporter
from megatron_llm_tpu.observability.profiler import ProfileTrigger
from megatron_llm_tpu.observability.registry import MetricsRegistry


# ---------------------------------------------------------------------------
# (a) span tracer: nesting, wraparound, Chrome-trace validity
# ---------------------------------------------------------------------------


def test_span_nesting_timestamps_contain():
    t = trace_mod.SpanTracer(capacity=64)
    with t.span("outer"):
        with t.span("inner"):
            pass
    events = t.snapshot()
    assert [name for _, name, *_ in events] == ["inner", "outer"]
    (_, _, in_ts, in_dur, _, _), (_, _, out_ts, out_dur, _, _) = events
    # the inner span's [ts, ts+dur] interval nests inside the outer's
    assert out_ts <= in_ts
    assert in_ts + in_dur <= out_ts + out_dur + 1e-9


def test_ring_buffer_wraparound():
    t = trace_mod.SpanTracer(capacity=16)
    for i in range(50):
        t.instant("e", i=i)
    assert len(t) == 16
    assert t.dropped == 34
    kept = [args["i"] for _, _, _, _, _, args in t.snapshot()]
    assert kept == list(range(34, 50))  # newest survive, oldest dropped


def test_snapshot_drain_starts_new_window():
    t = trace_mod.SpanTracer(capacity=16)
    t.instant("a")
    assert len(t.snapshot(drain=True)) == 1
    assert len(t) == 0
    t.instant("b")
    assert [n for _, n, *_ in t.snapshot()] == ["b"]


def test_chrome_trace_json_valid(tmp_path):
    t = trace_mod.SpanTracer(capacity=64)
    with t.span("phase", iteration=3):
        t.instant("mark")
    path = t.dump(str(tmp_path / "trace.json"))
    doc = json.load(open(path))
    assert isinstance(doc["traceEvents"], list)
    by_ph = {}
    for e in doc["traceEvents"]:
        # every event carries the Chrome-trace required fields
        assert {"name", "ph", "pid", "tid", "ts"} <= set(e) or e["ph"] == "M"
        by_ph.setdefault(e["ph"], []).append(e)
    (x,) = by_ph["X"]
    assert x["name"] == "phase" and x["dur"] >= 0
    assert x["args"] == {"iteration": 3}
    (i,) = by_ph["i"]
    assert i["name"] == "mark"
    # thread metadata row labels the recording thread
    (m,) = by_ph["M"]
    assert m["name"] == "thread_name"
    assert m["args"]["name"] == threading.current_thread().name
    assert doc["otherData"]["dropped_events"] == 0


def test_module_level_span_noop_when_unconfigured():
    trace_mod.disable()
    with trace_mod.span("x") as s:
        assert s is None  # shared null context
    trace_mod.instant("y")  # must not raise
    t = trace_mod.configure(capacity=32)
    try:
        with trace_mod.span("x"):
            pass
        assert len(t) == 1
    finally:
        trace_mod.disable()


def test_tracer_threads_labelled(tmp_path):
    t = trace_mod.SpanTracer(capacity=64)

    def work():
        with t.span("bg"):
            pass

    th = threading.Thread(target=work, name="my-worker")
    th.start()
    th.join()
    doc = t.to_chrome_trace()
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    # the worker thread has exited: its ident renders as thread-<id>
    assert any(e["args"]["name"].startswith(("my-worker", "thread-"))
               for e in metas)


# ---------------------------------------------------------------------------
# (b) registry: text format, escaping, types, concurrency
# ---------------------------------------------------------------------------


def test_prometheus_text_escaping():
    r = MetricsRegistry()
    r.gauge("odd-name", help="line one\nline \\two",
            labels={"path": 'a"b\\c\nd'}).set(1.5)
    text = r.render()
    # metric name sanitized into the Prometheus grammar
    assert "odd_name{" in text and "odd-name" not in text
    assert "# HELP odd_name line one\\nline \\\\two" in text
    assert 'path="a\\"b\\\\c\\nd"' in text
    assert text.endswith("\n")


def test_registry_types_and_conflicts():
    r = MetricsRegistry()
    c = r.counter("n_total")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    with pytest.raises(ValueError):
        r.gauge("n_total")  # one name, one type
    assert r.counter("n_total") is c  # get-or-create


def test_histogram_cumulative_buckets():
    r = MetricsRegistry()
    h = r.histogram("lat", buckets=[0.1, 1.0])
    for v in (0.05, 0.5, 0.7, 5.0):
        h.observe(v)
    text = r.render()
    assert 'lat_bucket{le="0.1"} 1' in text
    assert 'lat_bucket{le="1"} 3' in text
    assert 'lat_bucket{le="+Inf"} 4' in text
    assert "lat_count 4" in text
    assert "lat_sum 6.25" in text


def test_registry_concurrent_updates_exact():
    """The prefetch/writer/scheduler threads all publish concurrently;
    totals must be exact, not approximately right."""
    r = MetricsRegistry()
    c = r.counter("hits_total")
    g = r.gauge("depth")
    n_threads, per_thread = 8, 5000

    def work(k):
        for i in range(per_thread):
            c.inc()
            g.set(i)
            r.counter("labelled_total", labels={"t": str(k)}).inc()

    threads = [threading.Thread(target=work, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * per_thread
    for k in range(n_threads):
        assert r.counter("labelled_total",
                         labels={"t": str(k)}).value == per_thread


def test_publishing_switch_gates_timer_mirror():
    from megatron_llm_tpu.utils.timers import Timers

    reg = registry_mod.get_registry()
    reg.clear()
    registry_mod.set_publishing(False)
    try:
        t = Timers(1)
        t("quiet", 0).start()
        t("quiet").stop()
        t.gauge("quiet-gauge", 1.0)
        assert reg.names() == []
    finally:
        registry_mod.set_publishing(True)
    t = Timers(1)
    t("loud", 0).start()
    t("loud").stop()
    t.gauge("loud-gauge", 2.0)
    text = reg.render()
    assert 'mlt_timer_seconds_total{name="loud"}' in text
    assert 'mlt_driver_gauge{name="loud-gauge"} 2' in text


# ---------------------------------------------------------------------------
# (c) exporter endpoint + profile trigger
# ---------------------------------------------------------------------------


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), dict(e.headers)


def test_exporter_endpoint_smoke(tmp_path):
    r = MetricsRegistry()
    r.counter("smoke_total", help="smoke").inc(7)
    starts, stops = [], []
    trig = ProfileTrigger(str(tmp_path), default_steps=2, max_captures=2,
                          start_fn=starts.append, stop_fn=lambda: stops.append(1))
    ex = MetricsExporter(r, trig, host="127.0.0.1", port=0)
    port = ex.start()
    try:
        code, body, headers = _get(f"http://127.0.0.1:{port}/metrics")
        assert code == 200
        assert headers["Content-Type"].startswith("text/plain; version=0.0.4")
        assert "# TYPE smoke_total counter" in body
        assert "smoke_total 7" in body

        code, body, _ = _get(f"http://127.0.0.1:{port}/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"

        code, body, _ = _get(f"http://127.0.0.1:{port}/profile?steps=3")
        assert code == 200 and json.loads(body)["accepted"]
        # second request while the first is pending -> 409
        code, body, _ = _get(f"http://127.0.0.1:{port}/profile")
        assert code == 409 and not json.loads(body)["accepted"]

        code, body, _ = _get(f"http://127.0.0.1:{port}/nope")
        assert code == 404
    finally:
        ex.stop()
    # driver side runs the armed window: start at a boundary, stop after N
    assert trig.maybe_start(iteration=5) is not None
    assert starts and "iter00000005" in starts[0]
    assert not trig.step_done() and not trig.step_done()
    assert trig.step_done() and stops == [1]


def test_profile_trigger_budget_and_close(tmp_path):
    starts, stops = [], []
    trig = ProfileTrigger(str(tmp_path), max_captures=1,
                          start_fn=starts.append, stop_fn=lambda: stops.append(1))
    assert trig.request(1)["accepted"]
    trig.maybe_start(0)
    trig.close()  # open window closed exactly once
    assert stops == [1]
    res = trig.request(1)
    assert not res["accepted"] and "budget" in res["error"]
    assert not trig.request(0)["accepted"]  # steps must be >= 1


def test_exporter_without_trigger_503():
    ex = MetricsExporter(MetricsRegistry(), None, host="127.0.0.1", port=0)
    port = ex.start()
    try:
        code, body, _ = _get(f"http://127.0.0.1:{port}/profile?steps=1")
        assert code == 503
    finally:
        ex.stop()


# ---------------------------------------------------------------------------
# (d) flops vs a hand-counted tiny config
# ---------------------------------------------------------------------------


def test_flops_formula_hand_counted():
    from megatron_llm_tpu.models import make_config

    cfg = make_config(
        "llama2", num_layers=2, hidden_size=8, num_attention_heads=2,
        num_attention_heads_kv=1, ffn_hidden_size=16, vocab_size=32,
        seq_length=4, max_position_embeddings=8, tokenizer_type=None,
        micro_batch_size=2, global_batch_size=2,
    )
    # hand count: h=8, L=2, heads=2, kv=1, d=4, ffn=16, glu (swiglu) => 2
    # per layer: qkv 8*(2+2*1)*4=128; proj 2*4*8=64; mlp up 8*16*2=256;
    # mlp down 16*8=128  => 576;  embeddings (untied) 32*8*2=512
    assert flops_mod.param_count(cfg) == 576 * 2 + 512
    # 6*N + 6*L*h*s = 6*1664 + 6*2*8*4
    assert flops_mod.flops_per_token(cfg) == 6 * 1664 + 384
    assert flops_mod.flops_per_step(cfg) == (6 * 1664 + 384) * 2 * 4
    # MFU: known kind divides by its peak; unknown kind -> None
    tps = 1000.0
    mfu = flops_mod.mfu(cfg, tps, device_kind="TPU v5 lite")
    assert mfu == pytest.approx((6 * 1664 + 384) * tps / 197e12)
    assert flops_mod.mfu(cfg, tps, device_kind="cpu") is None
    assert flops_mod.mfu(cfg, 0.0, peak=1e12) is None
    # the driver's wrapper delegates here
    from megatron_llm_tpu.training import model_flops_per_token

    assert model_flops_per_token(cfg) == flops_mod.flops_per_token(cfg)


def test_peak_tables_single_source():
    """bench.py re-exports the flops.py peak tables — the measured MFU
    and the registry gauge must divide by the same numbers."""
    import bench

    assert bench.PEAK_BF16_FLOPS_BY_KIND is flops_mod.PEAK_BF16_FLOPS_BY_KIND
    assert bench.peak_flops  # still callable with its cpu-nominal fallback
    assert flops_mod.device_peak_flops("TPU v5") == 459e12
    assert flops_mod.device_peak_flops("TPU v5e somethingnew") == 197e12
    assert flops_mod.device_peak_flops("cpu") is None


# ---------------------------------------------------------------------------
# (e) linter: no device syncs inside observability/
# ---------------------------------------------------------------------------


def test_linter_forbids_device_sync_in_observability(tmp_path, capsys):
    from tools.linter import lint_file

    bad = tmp_path / "observability" / "thing.py"
    bad.parent.mkdir()
    bad.write_text("import jax\nx = jax.device_" + "get(y)\n")
    assert lint_file(str(bad)) == 1
    assert "device sync in observability/" in capsys.readouterr().out

    # the same line OUTSIDE an observability dir is fine
    ok = tmp_path / "elsewhere.py"
    ok.write_text("x = jax.device_" + "get(y)\n")
    assert lint_file(str(ok)) == 0

    blocked = tmp_path / "observability" / "wait.py"
    blocked.write_text("arr.block_until_" + "ready()\n")
    assert lint_file(str(blocked)) == 1


def test_observability_package_passes_linter():
    from tools.linter import lint_file

    pkg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "megatron_llm_tpu", "observability")
    issues = 0
    for name in os.listdir(pkg):
        if name.endswith(".py"):
            issues += lint_file(os.path.join(pkg, name))
    assert issues == 0


# ---------------------------------------------------------------------------
# (f) watchdog dumps the trace ring buffer on expiry
# ---------------------------------------------------------------------------


def test_watchdog_dumps_trace_on_expiry(tmp_path):
    import io

    from megatron_llm_tpu.resilience.watchdog import StepWatchdog

    tracer = trace_mod.SpanTracer(capacity=32)
    with tracer.span("data-wait"):
        pass
    trace_path = str(tmp_path / "trace_watchdog.json")
    stream = io.StringIO()
    exits = []
    dog = StepWatchdog(
        min_deadline=0.05, first_deadline=0.05, multiplier=1.0,
        trace_dump_fn=lambda: tracer.dump(trace_path, drain=False),
        exit_fn=exits.append, stream=stream,
    ).start()
    dog.arm(first=True)
    for _ in range(100):
        if exits:
            break
        import time

        time.sleep(0.05)
    assert exits == [43]
    out = stream.getvalue()
    assert "dumping" in out  # stack dump ran
    assert f"span trace dumped to {trace_path}" in out
    doc = json.load(open(trace_path))
    assert any(e["name"] == "data-wait" for e in doc["traceEvents"])
    # drain=False: the ring still holds the evidence
    assert len(tracer) == 1


def test_watchdog_trace_fallback_text(tmp_path):
    """Without --trace_dir the watchdog still prints a text timeline
    when a process-wide tracer exists."""
    import io
    import time

    from megatron_llm_tpu.resilience.watchdog import StepWatchdog

    tracer = trace_mod.configure(capacity=32)
    try:
        with trace_mod.span("dispatch", iteration=9):
            pass
        stream = io.StringIO()
        exits = []
        dog = StepWatchdog(
            min_deadline=0.05, first_deadline=0.05, multiplier=1.0,
            exit_fn=exits.append, stream=stream,
        ).start()
        dog.arm(first=True)
        for _ in range(100):
            if exits:
                break
            time.sleep(0.05)
        assert exits == [43]
        out = stream.getvalue()
        assert "TRACE: last" in out and "dispatch" in out
    finally:
        trace_mod.disable()


# ---------------------------------------------------------------------------
# (g) driver integration: trace phases, /metrics fields, bitwise parity
# ---------------------------------------------------------------------------


def _provider(scrape_at=None, scraped=None):
    """Synthetic deterministic data provider; optionally scrapes the live
    /metrics endpoint from inside the run (the prefetch worker thread)."""

    def provider(cfg, tokenizer, consumed):
        gbs, seq = cfg.training.global_batch_size, cfg.data.seq_length
        rng = np.random.default_rng(0)
        pool = [{
            "tokens": rng.integers(1, 512, (gbs, seq)).astype(np.int32),
            "labels": rng.integers(1, 512, (gbs, seq)).astype(np.int32),
            "loss_mask": np.ones((gbs, seq), np.float32),
        } for _ in range(2)]

        def gen():
            i = 0
            while True:
                if scrape_at is not None and i == scrape_at and not scraped:
                    from megatron_llm_tpu.observability import exporter

                    ex = exporter.active_exporter()
                    if ex is not None:
                        _, body, _ = _get(
                            f"http://127.0.0.1:{ex.port}/metrics")
                        scraped["text"] = body
                yield pool[i % 2]
                i += 1

        return gen(), None

    return provider


def _tiny_cfg(train_iters=10, **logging):
    from megatron_llm_tpu.models import make_config

    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, ffn_hidden_size=128, vocab_size=512,
        seq_length=32, max_position_embeddings=64, params_dtype="float32",
        use_flash_attn=False, micro_batch_size=2, global_batch_size=2,
        train_iters=train_iters, log_interval=2, eval_interval=0,
        tokenizer_type=None,
    )
    # the test harness exposes 8 virtual CPU devices; this loop is a
    # single-device run (gbs 2 does not divide dp 8)
    cfg.parallel.data_parallel_size = 1
    for k, v in logging.items():
        setattr(cfg.logging, k, v)
    return cfg


def test_pretrain_trace_and_metrics_end_to_end(tmp_path):
    """ISSUE 4 acceptance: a 10-step run with --trace_dir emits Chrome
    trace JSON whose spans include the async loop's phases, and a live
    /metrics scrape serves steady_mfu / tokens_per_sec / goodput."""
    from megatron_llm_tpu.training import pretrain

    trace_dir = str(tmp_path / "trace")
    scraped = {}
    cfg = _tiny_cfg(trace_dir=trace_dir, trace_steps=4, metrics_port=0)
    cfg.checkpoint.save = str(tmp_path / "ckpt")
    cfg.checkpoint.save_interval = 5
    cfg.checkpoint.async_save = True
    result = pretrain(cfg, data_iterators_provider=_provider(
        scrape_at=6, scraped=scraped))

    assert result["iteration"] == 10
    assert result["metrics_port"] and result["tokens_per_sec"] > 0
    assert result["steady_mfu"] is None  # CPU: no made-up MFU

    names = set()
    files = sorted(os.listdir(trace_dir))
    assert any(f.startswith("trace_final") for f in files)
    for f in files:
        if not f.endswith(".json"):
            continue
        doc = json.load(open(os.path.join(trace_dir, f)))
        assert isinstance(doc["traceEvents"], list)  # loads in Perfetto
        for e in doc["traceEvents"]:
            assert "ph" in e and "name" in e
        names |= {e["name"] for e in doc["traceEvents"]}
    for phase in ("data-wait", "dispatch", "metric-drain", "ckpt-flush",
                  "ckpt-write", "place-batch", "step-begin"):
        assert phase in names, f"missing span {phase} in {sorted(names)}"

    assert "text" in scraped, "mid-run /metrics scrape did not happen"
    for field in ("mlt_tokens_per_sec", "mlt_steady_mfu",
                  "mlt_goodput_fraction", "mlt_lm_loss", "mlt_iteration",
                  "mlt_batches_placed_total", "mlt_timer_seconds_total"):
        assert field in scraped["text"], f"missing {field} in /metrics"
    # exporter shut down with the run
    from megatron_llm_tpu.observability import exporter

    assert exporter.active_exporter() is None


def test_loss_bitwise_identical_with_observability(tmp_path):
    """ISSUE 4 acceptance: the loss trajectory with full observability on
    is bitwise-identical to all-off — instruments observe the loop, they
    never sit in its numerics."""
    from megatron_llm_tpu.training import pretrain

    off = pretrain(_tiny_cfg(), data_iterators_provider=_provider())
    on = pretrain(
        _tiny_cfg(trace_dir=str(tmp_path / "t"), trace_steps=3,
                  metrics_port=0),
        data_iterators_provider=_provider())
    assert off["loss_series"] == on["loss_series"]  # exact float equality
    assert float(off["last_metrics"]["lm loss"]) == float(
        on["last_metrics"]["lm loss"])


def test_generation_server_metrics_endpoint():
    """ISSUE 4 acceptance: /metrics on the generation server serves
    Prometheus text including engine slot occupancy."""
    import jax

    from megatron_llm_tpu.generation import ContinuousBatchingEngine
    from megatron_llm_tpu.generation.server import MegatronServer
    from megatron_llm_tpu.models import init_model_params, make_config
    from tests.test_generation import VOCAB, ToyTokenizer

    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, ffn_hidden_size=128, seq_length=128,
        max_position_embeddings=256, vocab_size=VOCAB,
        params_dtype="float32", use_flash_attn=False,
    )
    cfg.inference.max_batch_slots = 4
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    engine = ContinuousBatchingEngine(cfg, params, ToyTokenizer())
    srv = MegatronServer(engine)
    port = srv.start_background(port=0)
    try:
        code, body, headers = _get(f"http://127.0.0.1:{port}/metrics")
        assert code == 200
        assert headers["Content-Type"].startswith("text/plain; version=0.0.4")
        for field in ("mlt_engine_active_slots", "mlt_engine_max_slots",
                      "mlt_engine_queued_requests", "mlt_engine_free_pages",
                      "mlt_engine_pool_pages",
                      # ISSUE 5: prefix-cache telemetry
                      "mlt_engine_prefix_hit_tokens_total",
                      "mlt_engine_prefix_miss_tokens_total",
                      "mlt_engine_pages_cached",
                      "mlt_engine_pages_cow_copies_total",
                      # ISSUE 11: ragged-tick launch telemetry
                      "mlt_engine_tick_launches_total",
                      "mlt_engine_prefill_tokens_per_tick",
                      # ISSUE 12: honest TTFT decomposition histograms
                      "mlt_engine_queue_wait_seconds",
                      "mlt_engine_prefill_compute_seconds",
                      "mlt_engine_preempted_seconds",
                      # ISSUE 13: quantized-KV capacity telemetry
                      "mlt_engine_kv_pool_bytes",
                      "mlt_engine_kv_scale_bytes",
                      "mlt_engine_kv_dtype_info",
                      # ISSUE 15: compute/collective overlap mode
                      "mlt_tp_overlap_info",
                      # ISSUE 17: pipelined-dispatch telemetry
                      "mlt_engine_host_gap_seconds",
                      "mlt_engine_inflight_ticks",
                      "mlt_engine_tick_pipeline_depth",
                      # ISSUE 20: pipeline-parallel serving geometry
                      "mlt_engine_pp_stages",
                      "mlt_engine_kv_stage_bytes"):
            assert field in body, f"missing {field}"
        # an unpipelined engine reports one stage and a full-pool stage
        assert "mlt_engine_pp_stages 1" in body
        assert "mlt_engine_max_slots 4" in body
        assert 'mlt_engine_kv_dtype_info{kv_dtype="bf16"} 1' in body
        # a no-mesh engine reports the off mode at tp=1
        assert 'mlt_tp_overlap_info{mode="off",tp="1"} 1' in body
        # /health still answers alongside
        code, body, _ = _get(f"http://127.0.0.1:{port}/health")
        health = json.loads(body)
        assert code == 200 and health["status"] == "ok"
        # ISSUE 13: /health names the KV storage mode + byte budget
        assert health["kv_dtype"] == "bf16"
        assert health["kv_pool_bytes"] > 0
        assert health["kv_scale_bytes"] == 0
        assert health["peak_active_slots"] == 0
        # ISSUE 17: /health names the configured pipeline depth
        assert health["tick_pipeline_depth"] == 0
        # ISSUE 20: /health names the serving pipeline geometry — an
        # unpipelined engine reports one stage owning the whole pool
        assert health["pp"] == 1 and health["stages"] == 1
        assert health["kv_stage_bytes"] == health["kv_pool_bytes"]
    finally:
        srv.stop()


def test_engine_tick_metrics_count():
    """The engine's registry counters advance with real generations."""
    import jax

    from megatron_llm_tpu.generation import ContinuousBatchingEngine
    from megatron_llm_tpu.models import init_model_params, make_config
    from tests.test_generation import VOCAB, ToyTokenizer

    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, ffn_hidden_size=128, seq_length=128,
        max_position_embeddings=256, vocab_size=VOCAB,
        params_dtype="float32", use_flash_attn=False,
    )
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    engine = ContinuousBatchingEngine(cfg, params, ToyTokenizer())
    reg = registry_mod.get_registry()
    ticks0 = reg.counter("mlt_engine_ticks_total").value
    req0 = reg.counter("mlt_engine_requests_total").value
    engine.submit([5, 6, 7], 4, use_eod_for_termination=False)
    engine.run_until_idle()
    assert reg.counter("mlt_engine_requests_total").value == req0 + 1
    assert reg.counter("mlt_engine_ticks_total").value >= ticks0 + 4
    assert reg.gauge("mlt_engine_active_slots").value == 0  # drained


def test_on_demand_profile_trigger_in_pretrain(tmp_path, monkeypatch):
    """A /profile-style request armed before the run captures a bounded
    window at a step boundary inside the real loop."""
    from megatron_llm_tpu.observability import profiler as prof_mod
    from megatron_llm_tpu.training import pretrain

    calls = {"start": [], "stop": 0}

    def fake_start(logdir):
        calls["start"].append(logdir)

    def fake_stop():
        calls["stop"] += 1

    monkeypatch.setattr(prof_mod, "_jax_start", fake_start)
    monkeypatch.setattr(prof_mod, "_jax_stop", fake_stop)

    real_init = prof_mod.ProfileTrigger.__init__

    def patched_init(self, out_dir, **kw):
        kw.setdefault("start_fn", fake_start)
        kw.setdefault("stop_fn", fake_stop)
        real_init(self, out_dir, **kw)
        self.request(2)  # as if /profile?steps=2 landed before step 0

    monkeypatch.setattr(prof_mod.ProfileTrigger, "__init__", patched_init)
    pretrain(_tiny_cfg(train_iters=6), data_iterators_provider=_provider())
    assert len(calls["start"]) == 1
    assert "ondemand_000" in calls["start"][0]
    assert calls["stop"] == 1  # stopped after its window, not leaked


# ---------------------------------------------------------------------------
# (h) bench contract (tier-1 entries; the <3% gate runs in the slow lane)
# ---------------------------------------------------------------------------


def test_instrument_cost_microbench():
    """The per-step instrument bill, measured deterministically: replay
    one driver iteration's full instrumentation (spans, timer mirrors,
    gauges, trigger checks, amortized window dump) and time it alone.
    Tens of µs — far inside 3% of any real step."""
    import bench_observability as bo

    cost = bo.measure_instrument_cost(steps=500)
    # generous cap: even a 10ms CPU micro-step keeps 300µs/step inside 3%
    assert cost["instrument_cost_us_per_step"] < 300.0, cost


@pytest.mark.slow
def test_observability_overhead_gate(tmp_path):
    """ISSUE 4 acceptance gate: < 3% steps/sec overhead with full
    instrumentation on, at the bench's own CPU sanity shape.

    A wall-clock off/on A/B on this shared single-core host has a noise
    floor well above 3% (the bench's alternating-pair median tames it
    for evidence runs, but not enough for a hard CI gate), so the gate
    is asserted deterministically: the measured per-step instrument cost
    must be < 3% of the measured real step time — the same two numbers
    the wall-clock ratio divides, without the host drift between runs.
    The bitwise-parity half of the acceptance runs in the tier-1 lane
    (test_loss_bitwise_identical_with_observability)."""
    import bench_observability as bo
    from megatron_llm_tpu.models import make_config

    def make_cfg(iters):
        cfg = make_config(
            "llama2", num_layers=2, hidden_size=256,
            num_attention_heads=4, num_attention_heads_kv=4,
            ffn_hidden_size=512, vocab_size=1024, seq_length=128,
            max_position_embeddings=128, params_dtype="float32",
            use_flash_attn=False, micro_batch_size=4, global_batch_size=4,
            train_iters=iters, log_interval=10, eval_interval=0,
            tokenizer_type=None,
        )
        cfg.parallel.data_parallel_size = 1
        return cfg

    base = bo.run_mode(make_cfg, 1024, 128, 20, instrumented=False)
    step_us = 1e6 / max(base["steps_per_sec"] or 1e-9, 1e-9)
    cost = bo.measure_instrument_cost(steps=2000,
                                      trace_dir=str(tmp_path / "t"))
    overhead_pct = cost["instrument_cost_us_per_step"] / step_us * 100.0
    assert overhead_pct < bo.GATE_OVERHEAD_PCT, (cost, step_us)
