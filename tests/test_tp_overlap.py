"""ISSUE 15: fine-grained compute/collective overlap (parallel/overlap.py).

The parity matrix the acceptance criteria name:

* train-step loss + grad parity at tp=2/4, with and without sequence
  parallelism — ring vs off within rel 1e-4 (chunked-GEMM reassociation:
  tolerance, NOT bitwise — the overlap.py docstring documents why),
  with the ring mechanism machine-asserted in the compiled HLO
  (ppermute chain + ``forward-tp{N}-overlap`` scope metadata);
* engine greedy-token identity at tp=4, ragged AND legacy tick, with
  per-token log-probs within 5e-6 and the overlap span in a trace dump;
* int8 wire chunks vs the f32 ring (bounded by the per-hop rounding
  analysis) and vs the plain path;
* single-chip degradation: ``--tp_overlap ring`` at tp=1 is silently
  off — bitwise the no-mesh engine;
* cached_jit key regression: overlap engines never reuse non-overlap
  executables;
* graftcheck fixture: the overlap module passes the sweep with zero
  findings and zero ``noqa`` waivers.
"""

import copy
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from megatron_llm_tpu.core import parallel_state as ps
from megatron_llm_tpu.core import rng as rng_mod
from megatron_llm_tpu.models import init_model_params, make_config
from megatron_llm_tpu.parallel import overlap as ovl_mod
from megatron_llm_tpu.parallel.tp import param_shardings

VOCAB = 512  # pads identically at tp in {1, 2, 4} (test_tp_mesh.py note)


def _toy_cfg(tp: int, sp: bool = False, overlap: str = "off",
             quantized: bool = False):
    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=4, ffn_hidden_size=128, seq_length=64,
        max_position_embeddings=256, vocab_size=VOCAB,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype="float32", use_flash_attn=False,
    )
    cfg.parallel.tensor_model_parallel_size = tp
    cfg.parallel.data_parallel_size = 1
    cfg.parallel.sequence_parallel = sp
    cfg.parallel.tp_overlap = overlap
    cfg.parallel.quantized_tp_collectives = quantized
    return cfg


def _train_step_once(cfg, mesh):
    """One jitted train step; returns (loss, grad_norm, compiled HLO)."""
    from megatron_llm_tpu.training_step import make_jitted_train_step

    with ps.global_mesh(mesh):
        key = rng_mod.init_key(7)
        p_shard = param_shardings(
            mesh, jax.eval_shape(lambda k: init_model_params(cfg, k), key))
        # per-cell compile is the point of the parity matrix
        params = jax.jit(  # graftcheck: noqa[recompile-hazard]
            lambda k: init_model_params(cfg, k), out_shardings=p_shard)(key)
        step_fn, optimizer, sh = make_jitted_train_step(cfg, mesh, params)
        opt_state = optimizer.init(params)
        rng = np.random.RandomState(1)
        batch = {
            "tokens": rng.randint(2, VOCAB, (4, 64)).astype(np.int32),
            "labels": rng.randint(2, VOCAB, (4, 64)).astype(np.int32),
            "loss_mask": np.ones((4, 64), np.float32),
        }
        placed = sh["place_batch"](batch)
        lr = jnp.float32(1e-3)
        hlo = step_fn.lower(params, opt_state, placed, lr).compile().as_text()
        _, _, metrics = step_fn(params, opt_state, placed, lr)
        return float(metrics["lm loss"]), float(metrics["grad_norm"]), hlo


@pytest.mark.parametrize("tp,sp", [(2, False), (2, True),
                                   (4, False), (4, True)])
def test_train_parity_matrix(eight_devices, tp, sp):
    """Ring vs off at the same (tp, sp): loss rel <= 1e-4, grad norm rel
    <= 1e-3, and the ring program carries the decomposed mechanism."""
    mesh = ps.build_mesh(tensor_model_parallel_size=tp,
                         data_parallel_size=1, devices=eight_devices[:tp])
    off = _train_step_once(_toy_cfg(tp, sp, "off"), mesh)
    ring = _train_step_once(_toy_cfg(tp, sp, "ring"), mesh)
    loss_rel = abs(ring[0] - off[0]) / abs(off[0])
    gn_rel = abs(ring[1] - off[1]) / max(abs(off[1]), 1e-12)
    assert loss_rel <= 1e-4, (off[0], ring[0])
    assert gn_rel <= 1e-3, (off[1], ring[1])
    # mechanism, not vibes: the overlap scope is stamped on the ring HLO
    # and the ppermute chain exists beyond whatever XLA emits on its own
    scope = f"forward-tp{tp}-overlap"
    assert scope in ring[2], "ring HLO lost the overlap scope"
    assert scope not in off[2], "off HLO must stay byte-for-byte un-ringed"
    assert (ring[2].count("collective-permute")
            > off[2].count("collective-permute"))


def test_quantized_wire_bounded_vs_f32_ring(eight_devices):
    """--quantized_tp_collectives: int8 wire chunks vs the f32 ring,
    bounded by the per-hop rounding analysis (<= (tp-1) * scale/2 per
    element, scale = absmax/127 of the largest in-flight accumulator)."""
    mesh = ps.build_mesh(tensor_model_parallel_size=4,
                         data_parallel_size=1, devices=eight_devices[:4])
    cfg_f32 = _toy_cfg(4, overlap="ring")
    cfg_q = _toy_cfg(4, overlap="ring", quantized=True)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 8, 16).astype(np.float32))
    w = jnp.asarray(rng.randn(16, 12).astype(np.float32))

    def run(cfg):
        with ps.global_mesh(mesh):
            ovl = ovl_mod.overlap_params(cfg, mesh)
            assert ovl is not None

            def f(xx, ww):
                with ovl_mod.activate(ovl):
                    return ovl_mod.row_parallel(
                        cfg, {"kernel": ww}, xx,
                        lambda p, x_: x_ @ p["kernel"])

            return np.asarray(jax.jit(f)(x, w))

    y32 = run(cfg_f32)
    yq = run(cfg_q)
    # worst-case wire scale from the largest partial product; 3 hops
    partial_max = float(jnp.max(jnp.abs(x @ w))) * 4
    bound = 3 * (partial_max / 127.0) / 2 * 4  # generous: 4x analysis slack
    assert float(np.max(np.abs(yq - y32))) <= bound
    # and the f32 ring itself matches the plain matmul tightly
    assert float(np.max(np.abs(y32 - np.asarray(x @ w)))) < 1e-4


def _run_engine(cfg, params, mesh, ragged=True, n_req=3, tokens=8):
    from megatron_llm_tpu.generation.engine import ContinuousBatchingEngine

    eng = ContinuousBatchingEngine(cfg, params, None, max_slots=4,
                                   num_pages=64, page_size=16,
                                   ragged=ragged, mesh=mesh)
    prompts = [[2 + (7 * i + j) % (VOCAB - 2) for j in range(13)]
               for i in range(n_req)]
    reqs = [eng.submit(p, tokens, temperature=1.0, top_k=0, top_p=0.0,
                       seed=11 + i) for i, p in enumerate(prompts)]
    eng.run_until_idle()
    return eng, [(r.result()[0], list(r.log_probs)) for r in reqs]


@pytest.mark.parametrize("ragged", [True, False])
def test_engine_tp4_token_identity(eight_devices, ragged):
    """Engine greedy decode at tp=4: ring emits the SAME tokens as off
    (both tick modes); per-token log-probs within 5e-6."""
    cfg = _toy_cfg(1)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    mesh = ps.build_mesh(tensor_model_parallel_size=4,
                         data_parallel_size=1, devices=eight_devices[:4])
    c_off = copy.deepcopy(cfg)
    c_ring = copy.deepcopy(cfg)
    c_ring.parallel.tp_overlap = "ring"
    _, off = _run_engine(c_off, params, mesh, ragged=ragged)
    from megatron_llm_tpu.observability import trace as obs_trace

    tracer = obs_trace.configure()
    eng, ring = _run_engine(c_ring, params, mesh, ragged=ragged)
    for (t0, l0), (t1, l1) in zip(off, ring):
        assert t0 == t1
        np.testing.assert_allclose(l0, l1, atol=5e-6)
    # overlap observable: the forward-tp4-overlap span in the trace dump
    names = {e[1] for e in tracer.snapshot()}
    assert "forward-tp4-overlap" in names, sorted(names)
    assert eng._overlap_mode == "ring"
    obs_trace.disable()


def test_engine_tp4_quantized_wire_tokens(eight_devices):
    """int8 wire chunks keep greedy tokens identical on the toy shape
    (deterministic quantization; real-margin models — the PR 13 int8-KV
    lesson — are why the BENCH gate stays a short sanity horizon)."""
    cfg = _toy_cfg(1)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    mesh = ps.build_mesh(tensor_model_parallel_size=4,
                         data_parallel_size=1, devices=eight_devices[:4])
    c_off = copy.deepcopy(cfg)
    c_q = copy.deepcopy(cfg)
    c_q.parallel.tp_overlap = "ring"
    c_q.parallel.quantized_tp_collectives = True
    _, off = _run_engine(c_off, params, mesh)
    _, q = _run_engine(c_q, params, mesh)
    for (t0, _), (t1, _) in zip(off, q):
        assert t0 == t1


def test_single_chip_degradation_silently_off(eight_devices):
    """--tp_overlap ring at tp=1: overlap resolves to None (the flag is
    inert) and the engine is BITWISE the no-mesh engine."""
    cfg = _toy_cfg(1)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    _, base = _run_engine(cfg, params, None)
    c_ring = copy.deepcopy(cfg)
    c_ring.parallel.tp_overlap = "ring"
    mesh1 = ps.build_mesh(devices=eight_devices[:1])
    assert ovl_mod.overlap_params(c_ring, mesh1) is None
    eng, one = _run_engine(c_ring, params, mesh1)
    assert eng._overlap_mode == "off"
    for (t0, l0), (t1, l1) in zip(base, one):
        assert t0 == t1
        assert l0 == l1  # bitwise: no ring, no collectives at tp=1


def test_overlap_gating():
    """overlap_params returns None exactly when the ring must not build:
    mode off, no mesh, tp == 1, pp/cp layouts (foreign manual regions),
    fp8 forwards."""
    cfg = _toy_cfg(1, overlap="ring")
    devs = jax.devices()
    assert ovl_mod.overlap_params(cfg, None) is None
    mesh_tp4 = ps.build_mesh(tensor_model_parallel_size=4,
                             data_parallel_size=1, devices=devs[:4])
    assert ovl_mod.overlap_params(cfg, mesh_tp4) is not None
    off = _toy_cfg(1, overlap="off")
    assert ovl_mod.overlap_params(off, mesh_tp4) is None
    mesh_pp = ps.build_mesh(tensor_model_parallel_size=2,
                            pipeline_model_parallel_size=2,
                            data_parallel_size=1, devices=devs[:4])
    assert ovl_mod.overlap_params(cfg, mesh_pp) is None
    mesh_cp = ps.build_mesh(tensor_model_parallel_size=2,
                            context_parallel_size=2,
                            data_parallel_size=1, devices=devs[:4])
    assert ovl_mod.overlap_params(cfg, mesh_cp) is None
    fp8 = _toy_cfg(1, overlap="ring")
    fp8.model.fp8 = "e4m3"
    assert ovl_mod.overlap_params(fp8, mesh_tp4) is None
    bad = _toy_cfg(1)
    bad.parallel.tp_overlap = "banana"
    with pytest.raises(AssertionError):
        ovl_mod.overlap_params(bad, mesh_tp4)


def test_cached_jit_keys_never_cross_overlap_modes(eight_devices):
    """Regression: an overlap engine and a plain engine on the SAME mesh
    must key different executables — the effective mode rides in
    _mesh_statics (the config fingerprint alone cannot separate engines
    whose cfg matches but whose mesh makes the flag inert)."""
    from megatron_llm_tpu.generation.engine import ContinuousBatchingEngine

    cfg = _toy_cfg(1)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    mesh = ps.build_mesh(tensor_model_parallel_size=4,
                         data_parallel_size=1, devices=eight_devices[:4])
    c_ring = copy.deepcopy(cfg)
    c_ring.parallel.tp_overlap = "ring"
    e_off = ContinuousBatchingEngine(cfg, params, None, max_slots=4,
                                     num_pages=64, page_size=16, mesh=mesh)
    e_ring = ContinuousBatchingEngine(c_ring, params, None, max_slots=4,
                                      num_pages=64, page_size=16, mesh=mesh)
    assert ("tp_overlap", "off") == e_off._mesh_statics[-2:]
    assert ("tp_overlap", "ring") == e_ring._mesh_statics[-2:]
    assert e_off._mesh_statics != e_ring._mesh_statics
    # and the compiled tick programs are distinct cache entries
    assert e_off._tick() is not e_ring._tick()
    # a no-mesh engine also never collides with a ring engine even under
    # an overlap-requesting cfg (the inert-flag case)
    e_none = ContinuousBatchingEngine(c_ring, params, None, max_slots=4,
                                      num_pages=64, page_size=16)
    assert e_none._mesh_statics[-2:] == ("tp_overlap", "off")
    assert e_none._mesh_statics != e_ring._mesh_statics


def test_row_ring_under_dp_mesh(eight_devices):
    """The full-manual region names every mesh axis: a (dp=2, tp=4) mesh
    runs the ring with the batch sharded over dp and reduces only over
    tp — parity vs the plain projection."""
    mesh = ps.build_mesh(tensor_model_parallel_size=4,
                         data_parallel_size=2, devices=eight_devices[:8])
    cfg = _toy_cfg(4, overlap="ring")
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(4, 6, 16).astype(np.float32))
    w = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    with ps.global_mesh(mesh):
        ovl = ovl_mod.overlap_params(cfg, mesh)
        assert ovl is not None and ovl.data == 2

        def f(xx, ww):
            with ovl_mod.activate(ovl):
                return ovl_mod.row_parallel(cfg, {"kernel": ww}, xx,
                                            lambda p, x_: x_ @ p["kernel"])

        y = np.asarray(jax.jit(f)(x, w))
    np.testing.assert_allclose(y, np.asarray(x @ w), atol=1e-4)


def test_fallbacks_keep_plain_path():
    """Ineligible operands fall back to the plain projection even with an
    active context: int8-quantized kernels (kernel_q trees), shapes the
    tp cannot divide, and code already inside a foreign manual region."""
    devs = jax.devices()
    mesh = ps.build_mesh(tensor_model_parallel_size=4,
                         data_parallel_size=1, devices=devs[:4])
    cfg = _toy_cfg(1, overlap="ring")
    ovl = ovl_mod.overlap_params(cfg, mesh)
    x = jnp.ones((2, 4, 16), jnp.float32)
    sentinel = []

    def fb(p, x_):
        sentinel.append(True)
        return x_ @ p.get("kernel", jnp.eye(16, dtype=jnp.float32))

    with ovl_mod.activate(ovl):
        # quantized leaf: no "kernel" key
        ovl_mod.row_parallel(cfg, {"kernel_q": jnp.ones((16, 8))}, x, fb)
        assert sentinel.pop()
        # contraction dim not divisible by tp
        ovl_mod.row_parallel(
            cfg, {"kernel": jnp.ones((18, 8), jnp.float32)},
            jnp.ones((2, 4, 18), jnp.float32), fb)
        assert sentinel.pop()
        # column without SP: nothing to overlap
        ovl_mod.column_parallel(
            cfg, {"kernel": jnp.ones((16, 8), jnp.float32)}, x, fb)
        assert sentinel.pop()


def test_graftcheck_overlap_module_clean():
    """Tooling fixture (ISSUE 15): the overlap module passes the
    graftcheck sweep with ZERO findings and ZERO noqa waivers — new
    collective code enters the repo lint-clean, not baselined."""
    from tools.graftcheck import core

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "megatron_llm_tpu", "parallel", "overlap.py")
    with open(path) as f:
        src = f.read()
    assert "noqa" not in src, "overlap.py must not carry lint waivers"
    res = core.run([path], root=repo)
    errors = [f for f in res.findings if f.severity == "error"]
    assert res.files == 1
    assert not errors, [f"{f.rule}: {f.message}" for f in errors]
