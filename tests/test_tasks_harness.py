"""Downstream-task harness: zero-shot wikitext/lambada, GLUE/RACE finetune
(reference tasks/ analogs)."""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_llm_tpu.config import Config, apply_architecture
from megatron_llm_tpu.models import init_model_params, make_config


def tiny_gpt_cfg(**kw):
    defaults = dict(
        num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, vocab_size=256, seq_length=32,
        max_position_embeddings=64, params_dtype="float32",
        use_flash_attn=False,
    )
    defaults.update(kw)
    return make_config("llama2", **defaults)


def test_wikitext_ppl_matches_direct():
    from megatron_llm_tpu.models.language_model import loss_from_batch
    from tasks.zeroshot_gpt.evaluate import evaluate_wikitext_ppl

    cfg = tiny_gpt_cfg()
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    stream = np.asarray(
        jax.random.randint(jax.random.PRNGKey(1), (3 * 32 + 1,), 0, 256)
    )
    result = evaluate_wikitext_ppl(cfg, params, stream, batch_size=2)
    assert result["num_tokens"] == 96

    # direct computation over the same 3 windows
    rows = np.stack([stream[i * 32: i * 32 + 33] for i in range(3)])
    batch = {
        "tokens": jnp.asarray(rows[:, :-1]),
        "labels": jnp.asarray(rows[:, 1:]),
        "loss_mask": jnp.ones((3, 32), jnp.float32),
    }
    loss, _ = loss_from_batch(cfg, params, batch)
    np.testing.assert_allclose(
        result["ppl"], float(np.exp(float(loss))), rtol=1e-4
    )


def test_lambada_accuracy_on_memorized_model():
    """After overfitting a fixed continuation, strict lambada accuracy -> 1."""
    from megatron_llm_tpu.models.language_model import loss_from_batch
    from tasks.zeroshot_gpt.evaluate import evaluate_lambada

    cfg = tiny_gpt_cfg()
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    ctx = list(range(10, 26))
    tgt = [77, 88]
    row = np.asarray(ctx + tgt, np.int32)[None]
    batch = {
        "tokens": jnp.asarray(row[:, :-1]),
        "labels": jnp.asarray(row[:, 1:]),
        "loss_mask": jnp.ones((1, row.shape[1] - 1), jnp.float32),
    }
    grad_fn = jax.jit(jax.grad(lambda p: loss_from_batch(cfg, p, batch)[0]))
    for _ in range(150):
        g = grad_fn(params)
        params = jax.tree.map(lambda w, gg: w - 0.3 * gg, params, g)

    result = evaluate_lambada(cfg, params, [(ctx, tgt)], batch_size=2)
    assert result["accuracy"] == 1.0
    # a wrong target scores 0
    result2 = evaluate_lambada(cfg, params, [(ctx, [3, 4])], batch_size=2)
    assert result2["accuracy"] == 0.0


def test_lambada_jsonl_loader(tmp_path):
    from tasks.zeroshot_gpt.evaluate import load_lambada_jsonl

    p = tmp_path / "lambada.jsonl"
    p.write_text(json.dumps({"text": "12 34 56 78"}) + "\n")
    tokenize = lambda s: [int(w) for w in s.split()]
    samples = load_lambada_jsonl(str(p), tokenize)
    assert samples == [([12, 34, 56], [78])]


def test_pack_pair():
    from tasks.finetune_utils import pack_pair

    text, types, pad = pack_pair([1, 2, 3], [4, 5], 10, 100, 101, 0)
    assert text[:8].tolist() == [100, 1, 2, 3, 101, 4, 5, 101]
    assert types[:8].tolist() == [0, 0, 0, 0, 0, 1, 1, 1]
    assert pad.tolist() == [1] * 8 + [0] * 2
    # truncation keeps both segments
    text2, _, pad2 = pack_pair(list(range(1, 9)), list(range(10, 18)), 10,
                               100, 101, 0)
    assert int(pad2.sum()) == 10


def test_glue_processors(tmp_path):
    from tasks.glue.data import MNLIProcessor, QQPProcessor

    mnli = tmp_path / "mnli.tsv"
    header = "\t".join(f"c{i}" for i in range(12))
    row = ["x"] * 12
    row[8], row[9], row[11] = "a premise", "a hypothesis", "entailment"
    mnli.write_text(header + "\n" + "\t".join(row) + "\n")
    recs = MNLIProcessor().records(str(mnli))
    assert recs == [("a premise", "a hypothesis", 1)]

    qqp = tmp_path / "qqp.tsv"
    qqp.write_text(
        "id\tqid1\tqid2\tquestion1\tquestion2\tis_duplicate\n"
        "0\t1\t2\tq one\tq two\t1\n"
    )
    recs = QQPProcessor().records(str(qqp))
    assert recs == [("q one", "q two", 1)]


def test_race_reader(tmp_path):
    from tasks.race.data import read_race_records

    doc = {
        "article": "the article text",
        "questions": ["q1?"],
        "options": [["opt a", "opt b", "opt c", "opt d"]],
        "answers": ["C"],
    }
    p = tmp_path / "x.txt"
    p.write_text(json.dumps(doc))
    recs = read_race_records(str(tmp_path))
    assert recs == [("the article text", "q1?", ["opt a", "opt b", "opt c", "opt d"], 2)]


def _bert_task_cfg(num_iters=20, gbs=8):
    cfg = Config()
    apply_architecture(cfg, "bert")
    cfg.model.num_layers = 2
    cfg.model.hidden_size = 64
    cfg.model.num_attention_heads = 4
    cfg.model.vocab_size = 128
    cfg.model.max_position_embeddings = 32
    cfg.data.seq_length = 16
    cfg.data.tokenizer_type = "NullTokenizer"
    cfg.training.params_dtype = "float32"
    cfg.training.use_flash_attn = False
    cfg.training.micro_batch_size = gbs
    cfg.training.global_batch_size = gbs
    cfg.training.train_iters = num_iters
    cfg.training.eval_iters = 1
    cfg.training.eval_interval = num_iters
    cfg.optimizer.lr = 5e-3
    cfg.optimizer.lr_warmup_iters = 2
    cfg.logging.log_interval = 10
    cfg.finalize(n_devices=1)
    return cfg


def test_glue_style_finetune_learns_separable_task():
    """Classification finetune on a trivially separable synthetic task."""
    from tasks.finetune_utils import (
        ClassificationDataset,
        finetune_classification,
    )

    tokenize = lambda s: [int(w) for w in s.split()]
    rng = np.random.RandomState(0)
    records = []
    for _ in range(64):
        if rng.rand() < 0.5:
            records.append(("5 5 5", "5 5", 1))
        else:
            records.append(("9 9 9", "9 9", 0))
    ds = ClassificationDataset(records, tokenize, 16,
                               cls_id=120, sep_id=121, pad_id=0)
    cfg = _bert_task_cfg(num_iters=25)
    result = finetune_classification(cfg, ds, ds, num_classes=2)
    ev_loss = float(result["last_metrics"]["lm loss"])
    assert np.isfinite(ev_loss)
    # evaluate accuracy on the training set directly
    from megatron_llm_tpu.models.classification import (
        classification_forward,
    )

    batch = {k: jnp.asarray(np.stack([ds[i][k] for i in range(16)]))
             for k in ds[0]}
    logits = classification_forward(
        cfg, result["params"], batch["text"], batch["padding_mask"],
        batch["types"],
    )
    acc = float((np.argmax(np.asarray(logits), -1) ==
                 np.asarray(batch["label"])).mean())
    assert acc == 1.0, acc


def test_multiple_choice_forward_shapes():
    from megatron_llm_tpu.models.classification import (
        init_classification_params,
        multiple_choice_forward,
    )

    cfg = _bert_task_cfg()
    params = init_classification_params(cfg, jax.random.PRNGKey(0), 1)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 4, 16), 0, 120)
    pad = jnp.ones((2, 4, 16))
    scores = multiple_choice_forward(cfg, params, tokens, pad)
    assert scores.shape == (2, 4)


def test_msdp_eval_dispatch(tmp_path):
    """tasks/main.py MSDP-EVAL-F1 path (no model needed)."""
    import subprocess

    guess = tmp_path / "g.txt"
    ref = tmp_path / "r.txt"
    guess.write_text("the cat sat on the mat\n")
    ref.write_text("a cat sat on a mat\n")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tasks", "main.py"),
         "--task", "MSDP-EVAL-F1",
         "--guess_file", str(guess), "--answer_file", str(ref)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr
    assert "F1:" in r.stdout
