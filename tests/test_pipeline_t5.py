"""T5 under pipeline parallelism (models/t5.py:t5_pipeline_loss_fn) — the
analog of the reference's --pipeline_model_parallel_split_rank
encoder+decoder placement (megatron/parallel_state.py, schedules.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu.core.parallel_state import build_mesh, global_mesh
from megatron_llm_tpu.models import make_config
from megatron_llm_tpu.models.t5 import (
    init_t5_params,
    t5_loss_from_batch,
    t5_pipeline_loss_fn,
)


def t5_cfg(**kw):
    defaults = dict(
        num_layers=4,
        hidden_size=64,
        num_attention_heads=4,
        vocab_size=256,
        seq_length=24,
        decoder_seq_length=16,
        max_position_embeddings=64,
        params_dtype="float32",
        micro_batch_size=2,
        global_batch_size=8,
        train_iters=5,
        use_flash_attn=False,
        pipeline_model_parallel_size=2,
        pipeline_schedule="gpipe",
    )
    defaults.update(kw)
    cfg = make_config("t5", **defaults)
    cfg.parallel.num_micro_batches = 4
    return cfg


def t5_batch(cfg, key, gbs=8):
    se, sd = cfg.data.seq_length, cfg.data.decoder_seq_length
    ks = jax.random.split(key, 5)
    text_enc = jax.random.randint(ks[0], (gbs, se), 0, cfg.model.vocab_size)
    text_dec = jax.random.randint(ks[1], (gbs, sd), 0, cfg.model.vocab_size)
    labels = jax.random.randint(ks[2], (gbs, sd), 0, cfg.model.vocab_size)
    enc_len = jax.random.randint(ks[3], (gbs,), se - 5, se + 1)
    dec_len = jax.random.randint(ks[4], (gbs,), sd - 4, sd + 1)
    enc_mask = (jnp.arange(se)[None] < enc_len[:, None]).astype(jnp.int32)
    dec_mask = (jnp.arange(sd)[None] < dec_len[:, None]).astype(jnp.int32)
    return {
        "text_enc": text_enc,
        "text_dec": text_dec,
        "labels": labels,
        "enc_mask": enc_mask,
        "dec_mask": dec_mask,
        "loss_mask": dec_mask.astype(jnp.float32),  # real decoder positions
    }


def test_t5_pipeline_matches_unpipelined():
    """pp=2 GPipe T5 (encoder + decoder phases) reproduces the unpipelined
    loss and grads: cross-attention with padded encoder keys, causal+pad
    decoder self-attention, tied-embedding head with bias."""
    cfg = t5_cfg()
    params = init_t5_params(cfg, jax.random.PRNGKey(0))
    batch = t5_batch(cfg, jax.random.PRNGKey(1))

    cfg1 = t5_cfg(pipeline_model_parallel_size=1)
    ref_loss, ref_grads = jax.jit(jax.value_and_grad(
        lambda p: t5_loss_from_batch(cfg1, p, batch, deterministic=True)[0]
    ))(params)

    mesh = build_mesh(pipeline_model_parallel_size=2,
                      devices=jax.devices()[:2])
    with global_mesh(mesh):
        loss, grads = jax.jit(jax.value_and_grad(
            lambda p: t5_pipeline_loss_fn(cfg, mesh, p, batch, num_micro=4)[0]
        ))(params)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(ref_grads)[0],
        jax.tree_util.tree_flatten_with_path(grads)[0],
    ):
        assert pa == pb
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=5e-4, atol=5e-4,
            err_msg=f"grad mismatch at {pa}",
        )


def test_t5_pipeline_train_step():
    """Full jitted train step with the custom pipeline_loss descends."""
    from megatron_llm_tpu.training_step import make_jitted_train_step

    cfg = t5_cfg()
    mesh = build_mesh(pipeline_model_parallel_size=2)
    with global_mesh(mesh):
        params = init_t5_params(cfg, jax.random.PRNGKey(0))
        step, _o, sh = make_jitted_train_step(
            cfg, mesh, params, loss_fn=t5_loss_from_batch,
            pipeline_loss=t5_pipeline_loss_fn,
        )
        batch = sh["place_batch"](
            {k: np.asarray(v) for k, v in
             t5_batch(cfg, jax.random.PRNGKey(1)).items()}
        )
        o = sh["opt_state_value"]
        p = params
        losses = []
        for i in range(4):
            p, o, m = step(p, o, batch, i)
            losses.append(float(m["lm loss"]))
            assert np.isfinite(losses[-1])
        assert losses[-1] < losses[0]


def test_t5_pipeline_dropout_matches_unpipelined():
    """Round-3 VERDICT item 3: pipelined T5 with DROPOUT — per-microbatch
    keys split into (enc, dec) streams exactly as t5_forward does for the
    pp=1 grad-accumulation path, so the dropout masks are bit-identical
    and loss/grads match the microbatched unpipelined reference."""
    from megatron_llm_tpu.models.t5 import t5_forward
    from megatron_llm_tpu.ops.cross_entropy import softmax_cross_entropy

    cfg = t5_cfg(hidden_dropout=0.1, attention_dropout=0.1)
    params = init_t5_params(cfg, jax.random.PRNGKey(0))
    batch = t5_batch(cfg, jax.random.PRNGKey(1))
    base_key = jax.random.PRNGKey(42)
    M, gbs = 4, 8

    cfg1 = t5_cfg(pipeline_model_parallel_size=1,
                  hidden_dropout=0.1, attention_dropout=0.1)

    def ref_loss_fn(p):
        # per-microbatch forward with fold_in(base, i) (the key the pp=1
        # grad-accum path hands each microbatch), CE summed over the batch
        # and normalized by the FULL loss-mask sum (the pipelined head's
        # normalizer)
        full_denom = jnp.maximum(batch["loss_mask"].sum(), 1.0)
        total = jnp.float32(0.0)
        for i in range(M):
            mb = {k: v.reshape(M, gbs // M, *v.shape[1:])[i]
                  for k, v in batch.items()}
            logits = t5_forward(
                cfg1, p, mb["text_enc"], mb["text_dec"],
                mb["enc_mask"], mb["dec_mask"],
                dropout_key=jax.random.fold_in(base_key, i),
                deterministic=False,
            )
            ce = softmax_cross_entropy(logits, mb["labels"])
            total = total + (ce * mb["loss_mask"]).sum()
        return total / full_denom

    ref_loss, ref_grads = jax.jit(jax.value_and_grad(ref_loss_fn))(params)

    mesh = build_mesh(pipeline_model_parallel_size=2,
                      devices=jax.devices()[:2])
    with global_mesh(mesh):
        loss, grads = jax.jit(jax.value_and_grad(
            lambda p: t5_pipeline_loss_fn(
                cfg, mesh, p, batch, num_micro=4, dropout_key=base_key)[0]
        ))(params)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(ref_grads)[0],
        jax.tree_util.tree_flatten_with_path(grads)[0],
    ):
        assert pa == pb
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=5e-4, atol=5e-4,
            err_msg=f"grad mismatch at {pa}",
        )


def test_t5_pipeline_cp2_matches_unpipelined():
    """Round-3 VERDICT item 3: pipelined T5 under context parallelism —
    both stacks' self-attention cp-sharded (bidirectional ring for the
    encoder), cross-attention keys replicated over cp."""
    cfg = t5_cfg(context_parallel_size=2)
    params = init_t5_params(cfg, jax.random.PRNGKey(0))
    batch = t5_batch(cfg, jax.random.PRNGKey(1))

    cfg1 = t5_cfg(pipeline_model_parallel_size=1)
    ref_loss, ref_grads = jax.jit(jax.value_and_grad(
        lambda p: t5_loss_from_batch(cfg1, p, batch, deterministic=True)[0]
    ))(params)

    mesh = build_mesh(pipeline_model_parallel_size=2,
                      context_parallel_size=2,
                      devices=jax.devices()[:4])
    with global_mesh(mesh):
        loss, grads = jax.jit(jax.value_and_grad(
            lambda p: t5_pipeline_loss_fn(cfg, mesh, p, batch, num_micro=4)[0]
        ))(params)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(ref_grads)[0],
        jax.tree_util.tree_flatten_with_path(grads)[0],
    ):
        assert pa == pb
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=1e-3, atol=1e-3,
            err_msg=f"grad mismatch at {pa}",
        )
