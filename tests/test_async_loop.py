"""Async training loop (ISSUE 2): bitwise loss-trajectory parity of the
overlapped loop vs the blocking loop, prefetch-stage determinism and
shutdown, async-vs-sync checkpoint equivalence + exit barrier, and the
bench_train_loop.py evidence contract (mirroring test_bench_contract.py)."""

import json
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_llm_tpu.config import Config, apply_architecture
from megatron_llm_tpu.data.indexed_dataset import make_builder
from megatron_llm_tpu.data.prefetch import BatchPrefetcher, concat_chunks


@pytest.fixture
def toy_corpus(tmp_path):
    prefix = str(tmp_path / "corpus_text_document")
    rng = np.random.RandomState(0)
    builder = make_builder(prefix + ".bin", vocab_size=500)
    for _ in range(80):
        builder.add_doc(rng.randint(1, 500, size=rng.randint(40, 120)))
    builder.finalize(prefix + ".idx")
    return prefix


def small_cfg(toy_corpus, tmp_path, train_iters=6, *, dispatch_depth=2,
              prefetch_depth=2, rampup=None, save=None):
    cfg = Config()
    apply_architecture(cfg, "llama2")
    cfg.model.num_layers = 2
    cfg.model.hidden_size = 64
    cfg.model.num_attention_heads = 4
    cfg.model.num_attention_heads_kv = 2
    cfg.model.vocab_size = 512
    cfg.model.max_position_embeddings = 64
    cfg.data.seq_length = 32
    cfg.data.data_path = [toy_corpus]
    cfg.data.tokenizer_type = "NullTokenizer"
    cfg.training.params_dtype = "float32"
    cfg.training.use_flash_attn = False
    cfg.training.micro_batch_size = 2
    cfg.training.global_batch_size = 4
    cfg.training.train_iters = train_iters
    cfg.training.eval_iters = 2
    cfg.training.eval_interval = 0
    cfg.training.rampup_batch_size = rampup
    cfg.training.async_dispatch_depth = dispatch_depth
    cfg.training.prefetch_depth = prefetch_depth
    cfg.optimizer.lr = 1e-3
    cfg.checkpoint.save = save
    cfg.logging.log_interval = 2
    cfg.finalize(n_devices=1)
    return cfg


# ---------------------------------------------------------------------------
# (a) bitwise trajectory parity
# ---------------------------------------------------------------------------


def _series(result):
    return [(it, loss) for it, loss in result["loss_series"]]


def test_overlapped_trajectory_bitwise_identical(toy_corpus, tmp_path, capsys):
    """Deferred metrics + prefetch + async dispatch change WHEN the host
    observes results, never what the device computes: the fetched
    (iteration, lm loss) series must match the blocking loop bit for bit."""
    from megatron_llm_tpu.training import pretrain

    sync = pretrain(small_cfg(toy_corpus, tmp_path, 6,
                              dispatch_depth=0, prefetch_depth=0))
    async_ = pretrain(small_cfg(toy_corpus, tmp_path, 6,
                                dispatch_depth=2, prefetch_depth=2))
    assert len(_series(sync)) == 6
    assert _series(sync) == _series(async_)  # exact float equality
    assert float(sync["last_metrics"]["lm loss"]) == float(
        async_["last_metrics"]["lm loss"])

    out = capsys.readouterr().out
    # satellite: compile step fenced out of throughput reporting
    assert "first step (compile + warmup)" in out


def test_overlapped_trajectory_bitwise_identical_rampup(toy_corpus, tmp_path):
    """Same parity under a batch-size ramp: the prefetch worker replicates
    the chunked pulls + concatenation + post-ramp loader switch exactly."""
    from megatron_llm_tpu.training import pretrain

    # gbs ramps 2 -> 4 over 8 samples: iters at gbs 2, then the switch
    ramp = (2, 2, 8)
    sync = pretrain(small_cfg(toy_corpus, tmp_path, 5, dispatch_depth=0,
                              prefetch_depth=0, rampup=ramp))
    async_ = pretrain(small_cfg(toy_corpus, tmp_path, 5, dispatch_depth=2,
                                prefetch_depth=2, rampup=ramp))
    assert sync["consumed_samples"] == async_["consumed_samples"]
    assert len(_series(sync)) == 5
    assert _series(sync) == _series(async_)


# ---------------------------------------------------------------------------
# (b) prefetch stage: determinism, shutdown, errors
# ---------------------------------------------------------------------------


def _dict_stream(n, key="x"):
    for i in range(n):
        yield {key: np.full((2,), i, np.int32)}


def test_prefetch_deterministic_order_and_exhaustion():
    pf = BatchPrefetcher(_dict_stream(20), depth=3)
    got = [int(batch["x"][0]) for _, batch in pf]
    assert got == list(range(20))
    # exhaustion is terminal and repeatable
    with pytest.raises(StopIteration):
        next(pf)
    with pytest.raises(StopIteration):
        next(pf)
    assert pf.batches_out == 20


def test_prefetch_rampup_chunks_and_full_switch():
    """Chunked pulls follow the shadow gbs schedule; reaching full_gbs
    switches to the full-batch loader exactly once."""
    chunks = _dict_stream(4)  # 4 chunks of 2 rows while gbs == 4
    switched_with = []

    def switch(consumed):
        switched_with.append(consumed)
        return iter([{"x": np.full((4,), 100 + i, np.int32)}
                     for i in range(3)])

    pf = BatchPrefetcher(
        chunks, depth=2, chunk_size=2,
        gbs_fn=lambda consumed: 2 if consumed < 4 else 4,
        full_gbs=4, switch_source=switch,
    )
    items = list(pf)
    # two chunked steps at gbs 2 (one 2-row chunk each)...
    assert [g for g, _ in items[:2]] == [2, 2]
    assert [int(b["x"][0]) for _, b in items[:2]] == [0, 1]
    # ...then the switch (at consumed == 4) and full pass-through batches
    assert switched_with == [4]
    assert pf.switched_full
    assert [int(b["x"][0]) for _, b in items[2:]] == [100, 101, 102]
    assert all(b["x"].shape == (4,) for _, b in items[2:])


def test_prefetch_chunk_concat_token_idx():
    """Concatenation matches the driver loop: token_idx stays [s]."""
    src = iter([
        {"x": np.ones((2, 3), np.int32), "token_idx": np.arange(3)},
        {"x": 2 * np.ones((2, 3), np.int32), "token_idx": np.arange(3)},
    ])
    pf = BatchPrefetcher(src, depth=2, chunk_size=2,
                         gbs_fn=lambda consumed: 4)
    gbs, batch = next(pf)
    assert gbs == 4
    assert batch["x"].shape == (4, 3)
    assert batch["token_idx"].shape == (3,)  # batch-invariant, never stacked
    direct = concat_chunks([
        {"x": np.ones((2, 3), np.int32), "token_idx": np.arange(3)},
        {"x": 2 * np.ones((2, 3), np.int32), "token_idx": np.arange(3)},
    ])
    np.testing.assert_array_equal(batch["x"], direct["x"])


def test_prefetch_worker_exception_reraised_at_consumer():
    def bad_stream():
        yield {"x": np.zeros(1)}
        yield {"x": np.zeros(1)}
        raise ValueError("corrupt shard")

    pf = BatchPrefetcher(bad_stream(), depth=2)
    next(pf)
    next(pf)
    with pytest.raises(ValueError, match="corrupt shard"):
        next(pf)
    with pytest.raises(StopIteration):  # terminal after the error
        next(pf)


def test_prefetch_close_unblocks_full_queue():
    pf = BatchPrefetcher(_dict_stream(1000), depth=1)
    deadline = time.time() + 5.0
    while pf.qsize() < 1 and time.time() < deadline:
        time.sleep(0.01)  # worker now blocked on the full queue
    pf.close()
    assert not pf._thread.is_alive()


def test_prefetch_place_fn_applied():
    pf = BatchPrefetcher(_dict_stream(3), depth=2,
                         place_fn=lambda b: {k: v + 100 for k, v in b.items()})
    vals = [int(b["x"][0]) for _, b in pf]
    assert vals == [100, 101, 102]


# ---------------------------------------------------------------------------
# (c) async checkpointing
# ---------------------------------------------------------------------------


def _ckpt_cfg():
    cfg = Config()
    cfg.finalize(n_devices=1)
    return cfg


def test_async_checkpoint_identical_to_sync(tmp_path):
    """The async path writes the same logical checkpoint as the sync path:
    same entries (params / opt_state / meta / tracker), bitwise-identical
    restored arrays, same bookkeeping.  (Byte-level file names can't be
    compared: orbax's OCDBT store content-hashes its chunk files.)"""
    import jax.numpy as jnp

    from megatron_llm_tpu.checkpointing import (
        AsyncCheckpointSaver,
        load_checkpoint,
        read_tracker,
        save_checkpoint,
    )

    params = {"w": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
              "b": jnp.full((4,), 0.25, jnp.float32)}
    opt = {"m": jnp.ones((3, 4), jnp.float32) * 0.125}
    cfg = _ckpt_cfg()

    d_sync, d_async = str(tmp_path / "sync"), str(tmp_path / "async")
    save_checkpoint(cfg, d_sync, 7, params, opt, consumed_samples=28)
    saver = AsyncCheckpointSaver()
    saver.save(cfg, d_async, 7, params, opt, consumed_samples=28)
    saver.wait()
    assert not saver.pending

    metas = []
    for d in (d_sync, d_async):
        assert read_tracker(d) == (7, False)
        entries = set(os.listdir(os.path.join(d, "iter_0000007")))
        assert {"params", "opt_state", "meta.json"} <= entries
        p, o, it, consumed, meta = load_checkpoint(cfg, d, params, opt)
        assert it == 7 and consumed == 28
        for k in params:
            np.testing.assert_array_equal(np.asarray(p[k]),
                                          np.asarray(params[k]))
        np.testing.assert_array_equal(np.asarray(o["m"]), np.asarray(opt["m"]))
        metas.append(meta)
    assert metas[0] == metas[1]  # identical meta.json incl. saved config


def test_async_saver_single_inflight_barrier(tmp_path, monkeypatch):
    """A second save first JOINS the previous write — saves never overlap
    and never reorder."""
    import jax.numpy as jnp

    import megatron_llm_tpu.checkpointing as ck

    order = []
    real_save = ck.save_checkpoint

    def slow_save(cfg, d, it, *a, **k):
        order.append(("start", it))
        time.sleep(0.2)
        real_save(cfg, d, it, *a, **k)
        order.append(("end", it))

    monkeypatch.setattr(ck, "save_checkpoint", slow_save)
    saver = ck.AsyncCheckpointSaver()
    params = {"w": jnp.ones((2,))}
    saver.save(_ckpt_cfg(), str(tmp_path / "c"), 1, params)
    waited = saver.save(_ckpt_cfg(), str(tmp_path / "c"), 2, params)
    saver.wait()
    assert waited > 0.0  # the barrier actually waited for save #1
    assert order == [("start", 1), ("end", 1), ("start", 2), ("end", 2)]


def test_async_saver_error_surfaces_on_wait(tmp_path, monkeypatch):
    import jax.numpy as jnp

    import megatron_llm_tpu.checkpointing as ck

    def boom(*a, **k):
        raise OSError("disk full")

    monkeypatch.setattr(ck, "save_checkpoint", boom)
    saver = ck.AsyncCheckpointSaver()
    saver.save(_ckpt_cfg(), str(tmp_path / "c"), 1, {"w": jnp.ones(2)})
    with pytest.raises(OSError, match="disk full"):
        saver.wait()


def test_async_save_exit_midrun_lands_consistent_checkpoint(
        toy_corpus, tmp_path):
    """Acceptance: an exit mid-run (exit_interval — the same path a signal
    takes) with --async_save still lands a complete, loadable checkpoint:
    the exit barrier flushes the pending write before pretrain returns."""
    from megatron_llm_tpu.checkpointing import read_tracker
    from megatron_llm_tpu.training import pretrain

    cfg = small_cfg(toy_corpus, tmp_path, 8, save=str(tmp_path / "ckpt"))
    cfg.checkpoint.async_save = True
    cfg.checkpoint.save_interval = 2
    cfg.training.exit_interval = 3
    result = pretrain(cfg)
    assert result["exit_reason"] == "exit_interval"
    assert result["iteration"] == 3

    it, release = read_tracker(cfg.checkpoint.save)
    assert it == 3 and not release
    ckpt = os.path.join(cfg.checkpoint.save, "iter_0000003")
    assert os.path.isdir(os.path.join(ckpt, "params"))
    with open(os.path.join(ckpt, "meta.json")) as f:
        meta = json.load(f)
    assert meta["iteration"] == 3
    assert meta["consumed_samples"] == result["consumed_samples"]

    # and the checkpoint resumes cleanly
    cfg2 = small_cfg(toy_corpus, tmp_path, 5)
    cfg2.checkpoint.load = cfg.checkpoint.save
    result2 = pretrain(cfg2)
    assert result2["iteration"] == 5


# ---------------------------------------------------------------------------
# deferred metrics helpers: evaluate batching + timer gauges
# ---------------------------------------------------------------------------


def test_evaluate_batches_metric_fetch(toy_corpus, tmp_path, monkeypatch):
    """evaluate drains metric dicts through batched device_get calls — not
    one blocking float() per metric per iteration."""
    import jax
    import jax.numpy as jnp

    from megatron_llm_tpu import training as tr

    calls = []
    real_get = jax.device_get

    def counting_get(x):
        calls.append(x)
        return real_get(x)

    monkeypatch.setattr(tr.jax, "device_get", counting_get)
    cfg = small_cfg(toy_corpus, tmp_path, 4)
    batches = iter([{"i": i} for i in range(5)])
    out = tr.evaluate(
        cfg, None, lambda params, b: {"lm loss": jnp.float32(b["i"])},
        batches, max_iters=5)
    assert out["lm loss"] == pytest.approx((0 + 1 + 2 + 3 + 4) / 5)
    assert len(calls) == 1  # 5 iterations, ONE batched fetch


def test_timer_gauges_log_and_reset():
    from megatron_llm_tpu.utils.timers import Timers

    timers = Timers(log_level=1)
    timers.gauge("in-flight-depth", 1)
    timers.gauge("in-flight-depth", 3)
    timers.gauge("data-wait-ms", 5.0)
    log = timers.log()
    assert "in-flight-depth: 2.00 (max 3.00)" in log
    assert "data-wait-ms: 5.00" in log
    assert timers.log() == ""  # reset started a new interval

    quiet = Timers(log_level=0)  # gauges default to log level 1: gated
    quiet.gauge("in-flight-depth", 9)
    assert quiet.log() == ""


def test_step_times_bounded(toy_corpus, tmp_path):
    """The unbounded step_times list is gone: the result's loss series (and
    every other per-step record) is a bounded window."""
    from megatron_llm_tpu import training as tr

    assert tr._LOSS_SERIES_MAXLEN < 10_000
    result = tr.pretrain(small_cfg(toy_corpus, tmp_path, 4))
    assert len(result["loss_series"]) == 4
    assert result["warmup_time"] > 0
    assert result["steady_steps_per_sec"] > 0


# ---------------------------------------------------------------------------
# (d) bench_train_loop.py evidence contract (mirrors test_bench_contract.py)
# ---------------------------------------------------------------------------


import bench  # noqa: E402
from tools.tpu_watch import _bench_on_tpu  # noqa: E402


@pytest.fixture()
def evidence_dir(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "LAST_TPU_PATH",
                        str(tmp_path / "BENCH_LAST_TPU.json"))
    return tmp_path


def test_train_loop_bench_cpu_contract(evidence_dir):
    """Off-TPU: headline 0, the overlap measurement rides under cpu_sanity,
    TPU evidence goes to its own tagged file."""
    line = bench.cpu_contract_line({
        "metric": "train_loop_overlap_steps_s_1chip",
        "value": 6.9, "unit": "steps/s", "backend": "cpu",
        "speedup_vs_blocking": 2.14, "blocking_steps_per_sec": 3.2,
    }, tag="train_loop")
    assert line["value"] == 0.0 and line["unit"] == "steps/s"
    assert line["cpu_sanity"]["speedup_vs_blocking"] == 2.14
    assert not _bench_on_tpu(json.dumps(line))

    bench.persist_tpu_result({"metric": "train_loop", "value": 50.0,
                              "backend": "tpu"}, {}, tag="train_loop")
    assert bench.load_last_tpu(tag="train_loop")["value"] == 50.0
    assert bench.load_last_tpu() is None  # headline untouched


def test_train_loop_bench_in_watch_jobs():
    """The overlap bench is in the tunnel-up capture list with the bench
    contract (own watchdog => no subprocess timeout, bench predicate)."""
    from tools.tpu_watch import JOBS

    by_name = {name: (cmd, bounded, pred) for name, cmd, bounded, pred in JOBS}
    assert "bench_train_loop" in by_name
    cmd, bounded, pred = by_name["bench_train_loop"]
    assert cmd[-1].endswith("bench_train_loop.py")
    assert bounded is False and pred is _bench_on_tpu


@pytest.mark.slow
def test_train_loop_overlap_gate(toy_corpus, tmp_path):
    """ISSUE 2 acceptance gate: overlapped >= 1.5x blocking steps/sec with
    simulated host-side data latency (run through bench_train_loop's
    measurement path on a tiny shape)."""
    from bench_train_loop import make_provider, run_mode

    from megatron_llm_tpu.models import make_config

    # conftest pins an 8-device virtual CPU mesh: gbs must split over dp=8
    vocab, seq, mbs, gbs = 256, 64, 1, 8

    def make_cfg(iters):
        return make_config(
            "llama2", num_layers=2, hidden_size=128, num_attention_heads=4,
            num_attention_heads_kv=4, ffn_hidden_size=256, vocab_size=vocab,
            seq_length=seq, max_position_embeddings=seq,
            params_dtype="float32", use_flash_attn=False,
            micro_batch_size=mbs, global_batch_size=gbs, train_iters=iters,
            log_interval=10 ** 6, eval_interval=0, tokenizer_type=None,
        )

    calib = run_mode(make_cfg, 0.0, vocab, seq, 0, 0, 6)
    step_s = 1.0 / max(calib["steps_per_sec"], 1e-9)
    latency = min(max(step_s, 0.02), 0.5)
    blocking = run_mode(make_cfg, latency, vocab, seq, 0, 0, 12)
    overlapped = run_mode(make_cfg, latency, vocab, seq, 2, 2, 12)
    speedup = overlapped["steps_per_sec"] / blocking["steps_per_sec"]
    assert speedup >= 1.5, (
        f"overlap gate: {speedup:.2f}x < 1.5x "
        f"(blocking {blocking['steps_per_sec']:.2f}/s, "
        f"overlapped {overlapped['steps_per_sec']:.2f}/s)")
