"""Request-scoped tracing + flight recorder tests (ISSUE 12).

Gates: (1) the flight recorder is bounded in both dimensions (retired
ring + per-record events) with honest drop counters; (2) the latency
decomposition's phase buckets sum to the measured TTFT and total latency
(they are the same clock readings, bucketed); (3) recording is invisible
to the engine's output — tokens and log-probs are bitwise-identical with
the recorder on vs off; (4) one request traced across router -> replica
-> engine shares a single trace id in both tiers' spans, the replica's
``/debug/requests``, and the router's fleet aggregation, and the
response's server-side timing block carries a decomposition that sums to
its TTFT; (5) the watchdog's emergency dump lands the in-flight records;
(6) the recorder's lock annotations are really modeled by graftcheck's
lock-discipline rule (no vacuous cleanliness).
"""

import io
import json
import os
import time
import urllib.error
import urllib.request

import pytest

import jax

from megatron_llm_tpu.generation import ContinuousBatchingEngine
from megatron_llm_tpu.generation.server import MegatronServer
from megatron_llm_tpu.models import init_model_params, make_config
from megatron_llm_tpu.observability import flight as flight_mod
from megatron_llm_tpu.observability import trace as trace_mod
from megatron_llm_tpu.observability.flight import (
    NULL_RECORD,
    FlightRecorder,
)
from megatron_llm_tpu.serving.router.server import RouterServer

VOCAB = 67
GKW = dict(top_k=1, termination_id=10 ** 9)
TOL = 1e-5  # decomposition fields are rounded to 1e-6 in to_dict


@pytest.fixture(scope="module")
def toy_model():
    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=2, ffn_hidden_size=128, seq_length=128,
        max_position_embeddings=256, vocab_size=VOCAB,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype="float32", use_flash_attn=False,
    )
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _engine(cfg, params, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq", 128)
    return ContinuousBatchingEngine(cfg, params, None, **kw)


def _prompt(n, off=0):
    return [2 + ((i + off) * 7) % 60 for i in range(n)]


# ---------------------------------------------------------------------------
# Recorder unit: bounds, eviction, disabled mode
# ---------------------------------------------------------------------------


def test_recorder_done_ring_bounded():
    fl = FlightRecorder(capacity=4, events_per_request=8)
    for i in range(10):
        rec = fl.open(f"t{i}")
        rec.finish("ok")
        fl.close(rec)
    snap = fl.snapshot()
    assert len(snap) == 4
    # newest first: t9..t6 survive, t5..t0 evicted with an honest count
    assert [r["trace_id"] for r in snap] == ["t9", "t8", "t7", "t6"]
    assert fl.evicted == 6
    assert fl.inflight == 0


def test_record_event_log_bounded_keeps_terminal_events():
    fl = FlightRecorder(capacity=4, events_per_request=8)
    rec = fl.open("chatty")
    for i in range(50):
        rec.event("spec_tick", k=3, accepted=2)
    rec.mark_first_token()
    rec.finish("ok")
    d = rec.to_dict()
    assert len(d["events"]) == 8
    assert d["dropped_events"] == 50 + 2 - 8
    # the bounded ring drops OLDEST: terminal events always survive
    kinds = [e["kind"] for e in d["events"]]
    assert kinds[-2:] == ["first_token", "ok"]


def test_recorder_disabled_hands_out_null_record():
    fl = FlightRecorder(capacity=0)
    assert not fl.enabled
    rec = fl.open("x")
    assert rec is NULL_RECORD and not rec.enabled
    # every mutator is a no-op; close tolerates the null record
    rec.event("enqueue")
    rec.set_phase("decode")
    rec.mark_first_token()
    rec.finish("ok")
    fl.close(rec)
    assert fl.snapshot() == []


def test_snapshot_filters_and_caps():
    fl = FlightRecorder(capacity=8)
    for i in range(3):
        rec = fl.open("shared" if i < 2 else "other", index=i)
        rec.finish("ok")
        fl.close(rec)
    open_rec = fl.open("shared", index=99)  # stays in flight
    assert len(fl.lookup("shared")) == 3
    assert len(fl.lookup("other")) == 1
    assert len(fl.snapshot(n=2)) == 2
    # in-flight records come first
    assert fl.snapshot()[0]["phase"] == "queued"
    open_rec.finish("ok")
    fl.close(open_rec)


def test_decomposition_sums_exactly_synthetic():
    """Phase buckets partition the submit->done interval: their sum IS
    the measured latency (and the frozen TTFT buckets sum to TTFT)."""
    fl = FlightRecorder(capacity=4)
    rec = fl.open("t")
    time.sleep(0.01)                  # queued
    rec.set_phase("prefill")
    time.sleep(0.02)                  # prefill
    rec.set_phase("decode")
    time.sleep(0.005)
    rec.mark_first_token()
    time.sleep(0.01)                  # more decode
    rec.set_phase("preempted")
    time.sleep(0.01)
    rec.set_phase("decode")
    rec.finish("ok")
    fl.close(rec)
    d = rec.to_dict()
    assert abs(sum(d["ttft_decomposition"].values()) - d["ttft_s"]) < TOL
    assert abs(sum(d["decomposition"].values()) - d["latency_s"]) < TOL
    assert d["ttft_decomposition"]["preempted_s"] == 0.0
    assert d["decomposition"]["preempted_s"] >= 0.01 - TOL
    assert d["ttft_decomposition"]["prefill_s"] >= 0.02 - TOL


# ---------------------------------------------------------------------------
# Engine integration
# ---------------------------------------------------------------------------


def test_engine_records_lifecycle_and_decomposition(toy_model):
    cfg, params = toy_model
    eng = _engine(cfg, params)
    req = eng.submit(_prompt(20), 6, trace_id="trace-life", **GKW)
    eng.run_until_idle()
    req.result(timeout=60)
    recs = eng.flight.lookup("trace-life")
    assert len(recs) == 1
    r = recs[0]
    assert r["phase"] == "finished" and r["outcome"] == "ok"
    kinds = [e["kind"] for e in r["events"]]
    for expected in ("enqueue", "prefill", "prefill_chunk", "decode",
                     "first_token", "ok"):
        assert expected in kinds, f"missing {expected} in {kinds}"
    # the acceptance bar: components sum to the measured TTFT/latency
    assert abs(sum(r["ttft_decomposition"].values()) - r["ttft_s"]) < TOL
    assert abs(sum(r["decomposition"].values()) - r["latency_s"]) < TOL
    # and the engine's own TTFT agrees with the record's
    assert abs(req.ttft - r["ttft_s"]) < 1e-3
    assert r["prefill_compute_s"] > 0.0
    assert r["meta"]["prompt_tokens"] == 20


def test_engine_tokens_bitwise_identical_with_recorder_off(toy_model):
    """Recording must be invisible to the computation: same tokens and
    log-probs with the recorder on vs off (tracing on too)."""
    cfg, params = toy_model
    tracer = trace_mod.configure(capacity=4096)
    try:
        eng_on = _engine(cfg, params)
        assert eng_on.flight.enabled
        r_on = eng_on.submit(_prompt(24), 8, trace_id="parity", **GKW)
        eng_on.run_until_idle()
        toks_on, lps_on = r_on.result(timeout=60)
    finally:
        trace_mod.disable()
    eng_off = _engine(cfg, params, flight_records=0)
    assert not eng_off.flight.enabled
    r_off = eng_off.submit(_prompt(24), 8, **GKW)
    eng_off.run_until_idle()
    toks_off, lps_off = r_off.result(timeout=60)
    assert toks_on == toks_off
    assert lps_on == lps_off
    assert len(tracer) > 0  # tracing really was on for the on-arm


def test_preemption_recorded_with_resume(toy_model):
    cfg, params = toy_model
    eng = _engine(cfg, params, max_slots=1)
    victim = eng.submit(_prompt(16), 24, trace_id="victim", **GKW)
    # tick until the victim is decoding, then force-preempt it
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        eng.step()
        if victim._phase == "decode" and len(victim.generated) >= 2:
            break
    assert eng.preempt(victim)
    eng.run_until_idle()
    victim.result(timeout=60)
    r = eng.flight.lookup("victim")[0]
    kinds = [e["kind"] for e in r["events"]]
    assert "preempted" in kinds
    assert r["preemptions"] == 1
    assert r["decomposition"]["preempted_s"] > 0.0
    # resumed admission is recorded as a resume, not a fresh admit
    resume = [e for e in r["events"] if e["kind"] == "prefill"
              and e.get("args", {}).get("kind") == "resume"]
    assert resume, kinds
    assert abs(sum(r["decomposition"].values()) - r["latency_s"]) < TOL


def test_overload_leaves_a_record(toy_model):
    from megatron_llm_tpu.generation import EngineOverloaded

    cfg, params = toy_model
    eng = _engine(cfg, params, max_queue=1)
    eng.submit(_prompt(8), 4, trace_id="q1", **GKW)
    with pytest.raises(EngineOverloaded):
        eng.submit(_prompt(8), 4, trace_id="turned-away", **GKW)
    r = eng.flight.lookup("turned-away")[0]
    assert r["outcome"] == "overload"
    eng.run_until_idle()


def test_deadline_miss_attributed_by_phase(toy_model):
    from megatron_llm_tpu.observability import registry as obs_registry

    cfg, params = toy_model
    reg = obs_registry.get_registry()
    eng = _engine(cfg, params)  # fcfs never sheds: the miss retires
    req = eng.submit(_prompt(16), 2, ttft_deadline_ms=0.001,
                     trace_id="misser", seed=1, **GKW)
    eng.run_until_idle()
    req.result(timeout=60)
    rec = eng.flight.lookup("misser")[0]
    phase = max(
        (("queue", rec["ttft_decomposition"]["queue_wait_s"]
          + rec["ttft_decomposition"]["preempted_s"]),
         ("prefill", rec["ttft_decomposition"]["prefill_s"]),
         ("decode", rec["ttft_decomposition"]["decode_s"])),
        key=lambda kv: kv[1])[0]
    val = reg.counter("mlt_engine_deadline_miss_total",
                      labels={"kind": "ttft", "phase": phase}).value
    assert val >= 1


# ---------------------------------------------------------------------------
# Replica server: /debug/requests + timing metadata
# ---------------------------------------------------------------------------


def _get(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, dict(resp.headers), resp.read()


def _put(url, payload, headers=None):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), headers=hdrs,
        method="PUT")
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, dict(resp.headers), json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


@pytest.fixture(scope="module")
def fleet(toy_model):
    """Two continuous-batching replicas behind real MegatronServers on
    ephemeral ports (the test_router fixture shape)."""
    from tests.test_generation import ToyTokenizer

    cfg, params = toy_model
    servers, urls = [], []
    for _ in range(2):
        engine = ContinuousBatchingEngine(cfg, params, ToyTokenizer(),
                                          max_slots=4, max_seq=128)
        srv = MegatronServer(engine)
        port = srv.start_background(port=0)
        servers.append(srv)
        urls.append(f"http://127.0.0.1:{port}")
    yield servers, urls
    for srv in servers:
        try:
            srv.stop()
        except Exception:
            pass


def test_replica_debug_requests_and_timing(fleet):
    servers, urls = fleet
    tid = "replica-direct-trace"
    code, headers, body = _put(
        urls[0] + "/api",
        {"prompts": ["debug me please"], "tokens_to_generate": 6,
         "top_k": 1},
        headers={"X-MLT-Trace-Id": tid})
    assert code == 200
    assert headers["X-MLT-Trace-Id"] == tid
    timing = body["timing"]
    assert timing["trace_id"] == tid
    assert timing["replica_id"] == servers[0].replica_id
    assert timing["ttft_s"] is not None
    assert abs(float(headers["X-MLT-TTFT-S"]) - timing["ttft_s"]) < 1e-9
    assert abs(sum(timing["ttft_decomposition"].values())
               - timing["ttft_s"]) < TOL
    # the flight record is served on /debug/requests, filterable
    code, _, raw = _get(urls[0] + f"/debug/requests?trace_id={tid}")
    assert code == 200
    dbg = json.loads(raw)
    assert dbg["replica_id"] == servers[0].replica_id
    assert dbg["flight_recorder"] is True
    assert dbg["count"] == 1
    rec = dbg["requests"][0]
    assert rec["trace_id"] == tid and rec["outcome"] == "ok"
    # ?n= caps the listing
    code, _, raw = _get(urls[0] + "/debug/requests?n=0")
    assert json.loads(raw)["count"] == 0


def test_replica_mints_trace_id_when_absent(fleet):
    _, urls = fleet
    code, headers, body = _put(
        urls[0] + "/api",
        {"prompts": ["no trace header"], "tokens_to_generate": 2,
         "top_k": 1})
    assert code == 200
    minted = headers["X-MLT-Trace-Id"]
    assert minted and body["timing"]["trace_id"] == minted


def test_health_carries_ttft_ema(fleet):
    _, urls = fleet
    code, _, raw = _get(urls[0] + "/health")
    sched = json.loads(raw)["scheduler"]
    assert "ttft_ema_ms" in sched
    assert sched["ttft_ema_ms"] is not None  # requests already served


# ---------------------------------------------------------------------------
# End-to-end: one trace id across router -> replica -> engine
# ---------------------------------------------------------------------------


def test_e2e_trace_id_spans_router_and_replica(fleet, tmp_path):
    """The ISSUE 12 acceptance bar: one request's trace id appears in
    the router tier's and the serving replica's Perfetto span dumps and
    in both /debug/requests views, with decomposition fields summing to
    the measured TTFT."""
    servers, urls = fleet
    tracer = trace_mod.configure(capacity=8192)
    router = RouterServer(urls, policy="round_robin", poll_interval=30.0)
    tid = "e2e-fleet-trace-0001"
    try:
        port = router.start_background()
        base = f"http://127.0.0.1:{port}"
        code, headers, body = _put(
            base + "/api",
            {"prompts": ["trace me across the fleet"],
             "tokens_to_generate": 8, "top_k": 1},
            headers={"X-MLT-Trace-Id": tid})
        assert code == 200
        assert headers["X-MLT-Trace-Id"] == tid
        timing = body["timing"]
        assert timing["trace_id"] == tid
        assert abs(sum(timing["ttft_decomposition"].values())
                   - timing["ttft_s"]) < TOL

        # the serving replica's /debug/requests has the record...
        serving = [s for s in servers
                   if s.replica_id == timing["replica_id"]]
        assert len(serving) == 1
        direct = serving[0].debug_requests(trace_id=tid)
        assert direct["count"] == 1
        assert direct["requests"][0]["trace_id"] == tid

        # ...and the router's fleet aggregation finds it too, keyed by
        # replica url, without the caller knowing which replica served
        code, _, raw = _get(base + f"/debug/requests?trace_id={tid}")
        assert code == 200
        agg = json.loads(raw)
        assert agg["role"] == "router"
        hits = [(u, rep) for u, rep in agg["fleet"].items()
                if rep.get("count")]
        assert len(hits) == 1
        assert hits[0][1]["requests"][0]["trace_id"] == tid

        # span correlation: the router tier's route/forward spans AND
        # the replica tier's serve/enqueue spans carry the same id in
        # the Perfetto dump (one process here, two server tiers — the
        # trace_id attr is what correlates dumps across processes)
        dump = tmp_path / "fleet_trace.json"
        tracer.dump(str(dump), drain=False)
        events = json.load(open(dump))["traceEvents"]
        by_name = {}
        for e in events:
            if e.get("args", {}).get("trace_id") == tid:
                by_name.setdefault(e["name"], 0)
                by_name[e["name"]] += 1
        for span_name in ("router-route", "router-forward", "serve-api",
                          "engine-enqueue"):
            assert by_name.get(span_name), (
                f"no {span_name} span carries trace_id {tid}: {by_name}")

        # honest router TTFT: the histogram observed the replica's own
        # first-token stamp for the serving replica
        text = router.metrics_text()
        assert "mlt_router_ttft_seconds_bucket" in text
    finally:
        router.stop()
        trace_mod.disable()


# ---------------------------------------------------------------------------
# Watchdog emergency dump
# ---------------------------------------------------------------------------


def test_watchdog_dumps_flight_records(tmp_path):
    from megatron_llm_tpu.resilience.watchdog import StepWatchdog

    fl = FlightRecorder(capacity=8)
    rec = fl.open("stuck-request", prompt_tokens=64)
    rec.set_phase("prefill", kind="admit", slot=0)
    path = str(tmp_path / "flight_watchdog.json")
    stream = io.StringIO()
    exits = []
    dog = StepWatchdog(
        min_deadline=0.05, first_deadline=0.05, multiplier=1.0,
        flight_dump_fn=lambda: fl.dump(path),
        exit_fn=exits.append, stream=stream,
    ).start()
    dog.arm(first=True)
    deadline = time.monotonic() + 10
    while not exits and time.monotonic() < deadline:
        time.sleep(0.05)
    assert exits == [43]
    assert f"flight records dumped to {path}" in stream.getvalue()
    doc = json.load(open(path))
    assert doc["inflight"] == 1
    assert doc["records"][0]["trace_id"] == "stuck-request"
    assert doc["records"][0]["phase"] == "prefill"


def test_watchdog_flight_fallback_text():
    """Without a dump fn the watchdog prints the process recorder's
    in-flight tail — a hang report names the request state either way."""
    from megatron_llm_tpu.resilience.watchdog import StepWatchdog

    fl = FlightRecorder(capacity=8)
    fl.open("hanging", prompt_tokens=8)
    flight_mod.set_recorder(fl)
    stream = io.StringIO()
    exits = []
    try:
        dog = StepWatchdog(
            min_deadline=0.05, first_deadline=0.05, multiplier=1.0,
            exit_fn=exits.append, stream=stream,
        ).start()
        dog.arm(first=True)
        deadline = time.monotonic() + 10
        while not exits and time.monotonic() < deadline:
            time.sleep(0.05)
        assert exits == [43]
        out = stream.getvalue()
        assert "FLIGHT:" in out and "hanging" in out
    finally:
        flight_mod.set_recorder(None)


# ---------------------------------------------------------------------------
# Lock-annotation anti-vacuity (the ISSUE 10 idiom)
# ---------------------------------------------------------------------------


def test_lock_rule_verifies_flight_annotations():
    """The recorder's cross-thread state really is modeled by the
    graftcheck lock-discipline rule — the repo sweep's cleanliness over
    observability/flight.py is not vacuous."""
    import ast as ast_mod

    from tools.graftcheck import core
    from tools.graftcheck.rules.locks import LockDisciplineRule

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "megatron_llm_tpu", "observability",
                        "flight.py")
    rule = LockDisciplineRule()
    ctx = core.FileContext(path)
    expected = {
        "RequestRecord": ({"events", "phase", "phase_s", "t_first"},
                          {"_fold_locked", "_event_locked"}),
        "FlightRecorder": ({"_inflight", "_done", "_seq"}, set()),
    }
    found = set()
    for node in ast_mod.walk(ctx.tree):
        if isinstance(node, ast_mod.ClassDef) and node.name in expected:
            guards, holds = expected[node.name]
            model = rule._build(ctx, node)
            assert model is not None, f"{node.name}: no lock model"
            assert guards <= set(model.guards), (
                f"{node.name} missing guards: "
                f"{guards - set(model.guards)}")
            assert holds <= set(model.holds), (
                f"{node.name} missing holds: {holds - set(model.holds)}")
            found.add(node.name)
    assert found == set(expected)
