"""Ragged paged attention tests (ISSUE 11).

Gates:

1. **Bitwise parity matrix** — the ragged single-launch tick emits tokens
   AND log-probs bitwise-identical to the legacy split dispatch (decode
   tick + per-chunk prefill programs + flattened spec verify) across:
   decode-only, prefill-heavy, mixed, speculative (greedy and sampled),
   cache on/off, preemption/resume, and tp=4 (token identity).
2. **One launch per tick** — a mixed prefill+decode+spec tick dispatches
   exactly ONE compiled attention program, asserted via the engine's
   launch counter AND the ``engine-ragged-tick`` trace span (launches
   claimed in traces, not assumed).
3. **No recompiles** — tick-composition changes (different span/horizon
   mixes: all-decode, decode+prefill, multi-request prefill, drained)
   re-dispatch one executable (``_cache_size() == 1``).
4. **Token-level prefill budget** — ``SchedulerPolicy.prefill_budget`` is
   TOKENS: a budget of N admits multiple chunks from multiple requests
   into one tick; negative/typed-wrong budgets raise.
5. Telemetry: ``mlt_engine_tick_launches_total`` /
   ``mlt_engine_prefill_tokens_per_tick`` reach ``/metrics``.
"""

import numpy as np
import pytest

import jax

from megatron_llm_tpu.generation import ContinuousBatchingEngine, DraftModel
from megatron_llm_tpu.generation.scheduling import SchedulerPolicy

VOCAB = 67


@pytest.fixture(scope="module")
def models():
    from megatron_llm_tpu.models import init_model_params, make_config

    def mk(layers, hidden, heads, nkv, ffn):
        return make_config(
            "llama2", num_layers=layers, hidden_size=hidden,
            num_attention_heads=heads, num_attention_heads_kv=nkv,
            ffn_hidden_size=ffn, seq_length=256,
            max_position_embeddings=256, vocab_size=VOCAB,
            hidden_dropout=0.0, attention_dropout=0.0,
            params_dtype="float32", use_flash_attn=False,
        )

    cfg = mk(2, 64, 4, 2, 128)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    dcfg = mk(1, 32, 2, 2, 64)
    dparams = init_model_params(dcfg, jax.random.PRNGKey(1))
    return {"cfg": cfg, "params": params,
            "draft": DraftModel(dcfg, dparams)}


def _engine(models, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_seq", 128)
    return ContinuousBatchingEngine(models["cfg"], models["params"], None,
                                    **kw)


def _mixed_jobs(n_new=10):
    """Short prompts (instant decode), long prompts (multi-chunk
    prefill), a shared prefix (cache/COW traffic), and sampled rows."""
    shared = [2 + (i * 7) % 60 for i in range(48)]  # 3 full pages @ 16
    jobs = []
    for i in range(3):
        jobs.append(([5 + i, 9, 2 + i], n_new,
                     dict(top_k=1, termination_id=10 ** 9)))
    for i in range(2):
        tail = [3 + (i * 11 + j) % 60 for j in range(60 + 13 * i)]
        jobs.append((shared + tail[:128 - len(shared) - n_new], n_new,
                     dict(top_k=1, termination_id=10 ** 9)))
    jobs.append((list(shared), 8, dict(top_k=1, termination_id=10 ** 9)))
    for i in range(2):
        p = [3 + (i * 5 + j) % 60 for j in range(40 + 11 * i)]
        jobs.append((p, n_new, dict(temperature=0.9, top_k=7,
                                    seed=42 + i, termination_id=10 ** 9)))
    return jobs


def _run(eng, jobs):
    reqs = [eng.submit(p, n, **kw) for p, n, kw in jobs]
    eng.run_until_idle()
    return [r.result(timeout=120) for r in reqs]


def _assert_bitwise(a, b):
    assert len(a) == len(b)
    for (t0, l0), (t1, l1) in zip(a, b):
        assert t0 == t1, "ragged tokens diverged from legacy"
        assert l0 == l1, "ragged log-prob bits diverged from legacy"


# ---------------------------------------------------------------------------
# 1. bitwise parity matrix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cache", [True, False])
def test_parity_mixed(models, cache):
    legacy = _run(_engine(models, ragged=False, prefix_cache=cache),
                  _mixed_jobs())
    ragged = _run(_engine(models, ragged=True, prefix_cache=cache),
                  _mixed_jobs())
    _assert_bitwise(legacy, ragged)


def test_parity_decode_only(models):
    jobs = [([5, 9, 2 + i], 16, dict(top_k=1, termination_id=10 ** 9))
            for i in range(4)]
    _assert_bitwise(_run(_engine(models, ragged=False), jobs),
                    _run(_engine(models, ragged=True), jobs))


def test_parity_prefill_heavy(models):
    # prompts far longer than a chunk: most ticks are prefill-dominated
    jobs = [([2 + (i * 7 + j) % 60 for j in range(110 + 5 * i)], 6,
             dict(top_k=1, termination_id=10 ** 9)) for i in range(3)]
    _assert_bitwise(_run(_engine(models, ragged=False), jobs),
                    _run(_engine(models, ragged=True), jobs))


@pytest.mark.parametrize("cache", [True, False])
def test_parity_spec(models, cache):
    kw = dict(spec_k=3, spec_draft=models["draft"], spec_adaptive=False,
              prefix_cache=cache)
    legacy = _run(_engine(models, ragged=False, **kw), _mixed_jobs())
    ragged = _run(_engine(models, ragged=True, **kw), _mixed_jobs())
    _assert_bitwise(legacy, ragged)


def test_parity_spec_vs_nonspec_through_ragged(models):
    """The PR 9 losslessness contract survives the ragged rebuild:
    greedy spec rows through the ragged tick == plain ragged decode."""
    jobs = [j for j in _mixed_jobs() if "temperature" not in j[2]]
    plain = _run(_engine(models, ragged=True), jobs)
    spec = _run(_engine(models, ragged=True, spec_k=3,
                        spec_draft=models["draft"], spec_adaptive=False),
                jobs)
    _assert_bitwise(plain, spec)


def test_parity_preemption_resume(models):
    """A mid-decode preemption + trie resume under the ragged tick is
    bitwise the legacy path's resume (and the uninterrupted stream)."""
    def run(ragged, preempt_at):
        eng = _engine(models, ragged=ragged, sched_policy="fcfs")
        long = [2 + (j * 7) % 60 for j in range(48)]
        req = eng.submit(long, 14, top_k=1, termination_id=10 ** 9)
        other = eng.submit([5, 9, 2], 6, top_k=1, termination_id=10 ** 9)
        steps = 0
        while not req.finished:
            eng.step()
            steps += 1
            if steps == preempt_at and req._phase == "decode":
                assert eng.preempt(req)
        eng.run_until_idle()
        return [req.result(timeout=120), other.result(timeout=120)]

    base = run(True, 10 ** 9)   # never preempted
    for cut in (3, 6):
        _assert_bitwise(base, run(True, cut))
        _assert_bitwise(run(False, cut), run(True, cut))


def test_parity_tp4_token_identity(models, eight_devices):
    from megatron_llm_tpu.core import parallel_state as ps
    from megatron_llm_tpu.models import init_model_params, make_config

    # tp=4 needs kv heads % 4 == 0 — a 4-kv-head sibling of the toy model
    cfg = make_config(
        "llama2", num_layers=2, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=4, ffn_hidden_size=128, seq_length=256,
        max_position_embeddings=256, vocab_size=VOCAB,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype="float32", use_flash_attn=False)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    tpm = {"cfg": cfg, "params": params}

    jobs = _mixed_jobs(n_new=6)[:4]
    base = _run(_engine(tpm, ragged=True), jobs)
    mesh = ps.build_mesh(tensor_model_parallel_size=4,
                         data_parallel_size=1, devices=eight_devices[:4])
    tp = _run(_engine(tpm, ragged=True, mesh=mesh), jobs)
    for (t0, l0), (t1, l1) in zip(base, tp):
        assert t0 == t1  # tokens bitwise across tp
        np.testing.assert_allclose(l0, l1, atol=1e-5)


def test_parity_return_log_probs(models):
    """return_log_probs prompts take the legacy teacher-forced chunk
    carve-out in ragged mode: prompt AND generation log-probs bitwise."""
    jobs = [([2 + (j * 7) % 60 for j in range(40)], 8,
             dict(top_k=1, termination_id=10 ** 9, return_log_probs=True)),
            ([5, 9, 2], 8, dict(top_k=1, termination_id=10 ** 9))]

    def run(ragged):
        eng = _engine(models, ragged=ragged)
        reqs = [eng.submit(p, n, **kw) for p, n, kw in jobs]
        eng.run_until_idle()
        return [(r.result(timeout=120), r.prompt_log_probs) for r in reqs]

    legacy, ragged = run(False), run(True)
    for ((t0, l0), p0), ((t1, l1), p1) in zip(legacy, ragged):
        assert t0 == t1 and l0 == l1
        assert p0 == p1  # teacher-forced prompt scores bitwise too


# ---------------------------------------------------------------------------
# 2 + 3. single launch per mixed tick; no recompiles across compositions
# ---------------------------------------------------------------------------


def test_mixed_tick_single_launch_and_span(models):
    """A tick carrying decode slots + a prefill chunk + spec-verify
    blocks is ONE launch — counter And trace span agree."""
    from megatron_llm_tpu.observability import trace as obs_trace

    old = obs_trace.get_tracer()
    tracer = obs_trace.configure(capacity=4096)
    try:
        eng = _engine(models, ragged=True, spec_k=2,
                      spec_draft=models["draft"], spec_adaptive=False)
        # saturate decode first
        short = [eng.submit([5 + i, 9, 2], 24, top_k=1,
                            termination_id=10 ** 9) for i in range(3)]
        for _ in range(4):
            eng.step()
        # now a long prompt arrives: the next steps mix prefill + decode
        long = eng.submit([2 + (j * 7) % 60 for j in range(90)], 4,
                          top_k=1, termination_id=10 ** 9)
        mixed_seen = False
        for _ in range(4):
            eng.step()
            decoding = sum(r is not None and r._phase == "decode"
                           for r in eng._slots)
            if long._phase == "prefill" and decoding:
                mixed_seen = True
                assert eng.last_tick_launches == 1, (
                    "mixed prefill+decode+spec tick dispatched more than "
                    "one attention program")
        assert mixed_seen, "workload never produced a mixed tick"
        eng.run_until_idle()
        for r in short + [long]:
            r.result(timeout=120)
    finally:
        obs_trace._TRACER = old

    # events are (ph, name, ts, dur, ident, args) tuples
    spans = [e for e in tracer.snapshot()
             if e[1] == "engine-ragged-tick"]
    assert spans, "no engine-ragged-tick spans recorded"
    mixed = [e for e in spans
             if (e[5] or {}).get("prefill_tokens", 0) > 0
             and (e[5] or {}).get("active", 0) > 0]
    assert mixed, "no mixed tick span recorded in traces"
    assert all((e[5] or {}).get("launches") == 1 for e in spans), (
        "a ragged-tick span claimed more than one launch")


def test_legacy_mixed_tick_multi_launch(models):
    """The counter is honest: the legacy split path really does dispatch
    more than one program on a mixed tick (the thing ragged removes)."""
    eng = _engine(models, ragged=False)
    short = [eng.submit([5 + i, 9, 2], 24, top_k=1,
                        termination_id=10 ** 9) for i in range(3)]
    for _ in range(3):
        eng.step()
    long = eng.submit([2 + (j * 7) % 60 for j in range(90)], 4,
                      top_k=1, termination_id=10 ** 9)
    seen = 0
    for _ in range(4):
        eng.step()
        if long._phase == "prefill":
            seen = max(seen, eng.last_tick_launches)
    assert seen >= 2, "legacy mixed tick should be >= 2 launches"
    eng.run_until_idle()
    for r in short + [long]:
        r.result(timeout=120)


def test_composition_changes_reuse_bounded_executables(models):
    """The recompile-hazard gate: all-decode, mixed, multi-request
    prefill, spec depths, drained — every composition re-dispatches a
    BOUNDED executable set (one per bucketed live-prefill-row count, at
    most 1 + prefill_rows/prefill_chunk) and none of them ever
    re-traces: span/horizon/block-table metadata is data-carried, never
    static."""
    eng = _engine(models, ragged=True, spec_k=2,
                  spec_draft=models["draft"], spec_adaptive=False)
    _run(eng, _mixed_jobs())            # mixed compositions
    _run(eng, _mixed_jobs(n_new=4)[:2])  # different mix
    bound = 1 + eng.prefill_rows // eng.prefill_chunk
    assert eng._ragged_fns, "ragged tick never compiled"
    assert len(eng._ragged_fns) <= bound, (
        "tick-composition changes grew the executable set past the "
        "shape bound")
    for fn in eng._ragged_fns.values():
        assert fn._cache_size() == 1, (
            "a ragged executable re-traced on a composition change")

    eng2 = _engine(models, ragged=True)
    _run(eng2, _mixed_jobs())
    assert len(eng2._ragged_fns) <= 1 + (eng2.prefill_rows
                                         // eng2.prefill_chunk)
    for fn in eng2._ragged_fns.values():
        assert fn._cache_size() == 1


# ---------------------------------------------------------------------------
# 4. token-level prefill budget
# ---------------------------------------------------------------------------


class _TokenBudget(SchedulerPolicy):
    name = "_token_budget_test"
    barrier_admission = True

    def __init__(self, tokens, **kw):
        super().__init__(**kw)
        self.tokens = tokens

    def prefill_budget(self, prefilling, state):
        return self.tokens


def test_budget_admits_multiple_chunks_multiple_requests(models):
    """ISSUE 11 regression: prefill_budget is TOKENS — a 192-token budget
    packs 3 chunks spanning TWO requests into one tick."""
    eng = _engine(models, max_seq=256, ragged=True,
                  sched_policy=_TokenBudget(192), prefill_budget=192)
    r1 = eng.submit([2 + (j % 60) for j in range(150)], 4,
                    top_k=1, termination_id=10 ** 9)
    r2 = eng.submit([3 + (j % 60) for j in range(100)], 4,
                    top_k=1, termination_id=10 ** 9)
    eng.step()
    # r1's bucketed prompt (160) fills entirely; r2 gets the rest (32)
    assert r1._fill_pos == 160 and r2._fill_pos == 32, (
        r1._fill_pos, r2._fill_pos)
    assert eng.last_tick_launches == 1
    eng.run_until_idle()
    got = [r1.result(timeout=60), r2.result(timeout=60)]
    # aggressive packing is still bitwise the default pacing
    base = _run(_engine(models, max_seq=256, ragged=True),
                [([2 + (j % 60) for j in range(150)], 4,
                  dict(top_k=1, termination_id=10 ** 9)),
                 ([3 + (j % 60) for j in range(100)], 4,
                  dict(top_k=1, termination_id=10 ** 9))])
    _assert_bitwise(base, got)


def test_budget_validated_as_tokens(models):
    """Negative or non-int budgets are policy bugs and raise."""
    eng = _engine(models, ragged=True, sched_policy=_TokenBudget(-1))
    eng.submit([2 + (j % 60) for j in range(80)], 2,
               top_k=1, termination_id=10 ** 9)
    with pytest.raises(ValueError, match="TOKENS"):
        eng.step()
    eng2 = _engine(models, ragged=True, sched_policy=_TokenBudget(2.5))
    eng2.submit([2 + (j % 60) for j in range(80)], 2,
                top_k=1, termination_id=10 ** 9)
    with pytest.raises(ValueError, match="TOKENS"):
        eng2.step()


def test_budget_floor_keeps_prefill_alive(models):
    """A zero budget still advances one chunk per tick (liveness — the
    legacy `max(1, ...)` guarantee, now in token units)."""
    eng = _engine(models, ragged=True, sched_policy=_TokenBudget(0))
    req = eng.submit([2 + (j % 60) for j in range(80)], 2,
                     top_k=1, termination_id=10 ** 9)
    eng.run_until_idle()
    req.result(timeout=60)


# ---------------------------------------------------------------------------
# 5. telemetry surface
# ---------------------------------------------------------------------------


def test_launch_metrics_on_scrape(models):
    from megatron_llm_tpu.observability import registry as obs_registry

    reg = obs_registry.get_registry()
    before = reg.counter("mlt_engine_tick_launches_total").value
    eng = _engine(models, ragged=True)
    _run(eng, _mixed_jobs(n_new=4)[:3])
    text = reg.render()
    assert "mlt_engine_tick_launches_total" in text
    assert "mlt_engine_prefill_tokens_per_tick" in text
    assert reg.counter("mlt_engine_tick_launches_total").value > before
    # ragged mode: launches == non-idle ticks
    assert eng.tick_launches == eng.ticks, (eng.tick_launches, eng.ticks)
