"""Pipeline-parallel serving tick + vocab-parallel head ring (ISSUE 20,
parallel/pp_serve.py + the vocab_ring slots of parallel/overlap.py).

The parity contract the acceptance criteria name:

* engine greedy decode at pp=2/4 emits the SAME tokens as the flat
  (no-mesh) engine — ragged AND legacy AND chained/pipelined tick,
  prefix cache on/off, speculative decoding on/off — with per-token
  log-probs within 5e-6 (microbatched stage scan: same GEMMs, but XLA
  may tile the per-stage programs differently → tolerance on log-probs,
  identity on tokens);
* preempt/resume churn under pp lands on the uninterrupted run's bits;
* per-stage KV storage is 1/pp of the tp-only pool (kv_stage_bytes);
* the vocab-ring head GEMM is machine-asserted in HLO (ppermute chain
  + ``vocab-ring-tp{N}`` scope), numerically matches the plain
  all-gather head, and keeps engine greedy tokens identical;
* pp/vocab-ring geometry rides in ``_mesh_statics`` so pp engines never
  reuse tp-only executables (cached_jit is process-wide);
* inert flags degrade BITWISE: a pp=1 mesh builds no stage machinery,
  ``--vocab_ring`` at tp=1 resolves to None;
* observables: the ``engine-pp-tick`` span in a trace dump, the
  ``stage-permute`` scope in the compiled tick program, and the
  ``mlt_engine_pp_stages`` / ``mlt_engine_kv_stage_bytes`` gauges.
"""

import copy

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.core import parallel_state as ps
from megatron_llm_tpu.generation.engine import ContinuousBatchingEngine
from megatron_llm_tpu.models import init_model_params, make_config
from megatron_llm_tpu.parallel import compat as compat_mod
from megatron_llm_tpu.parallel import overlap as ovl_mod
from megatron_llm_tpu.parallel import pp_serve as pp_serve_mod

VOCAB = 512  # divisible by tp^2 for tp in {1, 2, 4} (vocab-ring columns)


@pytest.fixture(autouse=True)
def _restore_partitioner():
    """pp>1 engines flip jax_use_shardy_partitioner and hold it for their
    lifetime (parallel/compat.py) — restore after each test so this file
    leaks no partitioner state into the rest of the suite."""
    prev = bool(jax.config.jax_use_shardy_partitioner)
    yield
    compat_mod.restore_partitioner(prev)


def _toy_cfg(num_layers=4, tp=1, vocab_ring=False):
    cfg = make_config(
        "llama2", num_layers=num_layers, hidden_size=64,
        num_attention_heads=4, num_attention_heads_kv=4,
        ffn_hidden_size=128, seq_length=64, max_position_embeddings=256,
        vocab_size=VOCAB, hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype="float32", use_flash_attn=False,
    )
    cfg.parallel.tensor_model_parallel_size = tp
    cfg.parallel.data_parallel_size = 1
    cfg.parallel.vocab_ring = vocab_ring
    return cfg


@pytest.fixture(scope="module")
def toy_params():
    return init_model_params(_toy_cfg(), jax.random.PRNGKey(0))


def _run_engine(cfg, params, mesh, n_req=3, tokens=8, **kw):
    eng = ContinuousBatchingEngine(cfg, params, None, max_slots=4,
                                   num_pages=64, page_size=16,
                                   mesh=mesh, **kw)
    prompts = [[2 + (7 * i + j) % (VOCAB - 2) for j in range(13)]
               for i in range(n_req)]
    reqs = [eng.submit(p, tokens, temperature=1.0, top_k=0, top_p=0.0,
                       seed=11 + i) for i, p in enumerate(prompts)]
    eng.run_until_idle()
    return eng, [(r.result()[0], list(r.log_probs)) for r in reqs]


def _check(base, other, label, atol=5e-6):
    for (t0, l0), (t1, l1) in zip(base, other):
        assert t0 == t1, (label, t0, t1)
        np.testing.assert_allclose(l0, l1, atol=atol, err_msg=label)


def _pp_mesh(devs, pp, tp=1):
    return ps.build_mesh(tensor_model_parallel_size=tp,
                         pipeline_model_parallel_size=pp,
                         data_parallel_size=1, devices=devs[:pp * tp])


# ---------------------------------------------------------------------------
# tentpole: pp=2/4 greedy parity vs the flat engine, all tick modes
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("pp", [2, 4])
def test_engine_pp_token_identity(eight_devices, pp):
    """Ragged tick at pp stages: same greedy tokens as the flat engine,
    log-probs within 5e-6, per-stage KV bytes exactly pool/pp."""
    cfg = _toy_cfg()
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    _, base = _run_engine(cfg, params, None)  # flat arm FIRST (GSPMD)
    eng, out = _run_engine(copy.deepcopy(cfg), params,
                           _pp_mesh(eight_devices, pp))
    _check(base, out, f"pp={pp} ragged")
    assert eng._pp == pp and eng._ppc is not None
    assert eng.pool.pp == pp
    assert eng.pool.kv_stage_bytes() == eng.pool.kv_pool_bytes() // pp


def test_engine_pp_tick_modes(eight_devices, toy_params):
    """pp=2 parity holds on the legacy tick, the chained/pipelined tick
    (tick_pipeline_depth=2), and with the prefix cache off."""
    cfg = _toy_cfg()
    params = toy_params
    _, b_legacy = _run_engine(cfg, params, None, ragged=False)
    _, b_chain = _run_engine(cfg, params, None, tick_pipeline_depth=2)
    _, b_nocache = _run_engine(cfg, params, None, prefix_cache=False)
    mesh = _pp_mesh(eight_devices, 2)
    _, p = _run_engine(copy.deepcopy(cfg), params, mesh, ragged=False)
    _check(b_legacy, p, "pp2 legacy tick")
    _, p = _run_engine(copy.deepcopy(cfg), params, mesh,
                       tick_pipeline_depth=2)
    _check(b_chain, p, "pp2 chained tick")
    _, p = _run_engine(copy.deepcopy(cfg), params, mesh,
                       prefix_cache=False)
    _check(b_nocache, p, "pp2 cache off")


def test_engine_pp_speculative(eight_devices, toy_params):
    """Speculative decoding under pp: the 2-layer draft splits over the
    same stages; greedy output matches the flat spec engine."""
    from megatron_llm_tpu.generation.speculative import resolve_draft

    cfg = _toy_cfg()
    draft = resolve_draft(
        "llama2:num_layers=2,hidden_size=32,num_attention_heads=4,"
        "num_attention_heads_kv=4,ffn_hidden_size=64", cfg)
    _, base = _run_engine(cfg, toy_params, None, spec_k=2, spec_draft=draft)
    _, out = _run_engine(copy.deepcopy(cfg), toy_params,
                         _pp_mesh(eight_devices, 2),
                         spec_k=2, spec_draft=draft)
    _check(base, out, "pp2 spec on")


def test_engine_pp_preempt_resume(eight_devices, toy_params):
    """Preempt a decoding request mid-stream on a pp=2 engine, let it
    resume: tokens identical to the uninterrupted FLAT run, log-probs
    within the pp tolerance (resume is bitwise w.r.t. the same engine;
    the cross-arm comparison carries the usual 5e-6)."""
    cfg = _toy_cfg()
    prompt = [2 + (j * 7) % (VOCAB - 2) for j in range(13)]
    flat = ContinuousBatchingEngine(cfg, toy_params, None, max_slots=4,
                                    num_pages=64, page_size=16)
    ref = flat.submit(prompt, 24, temperature=1.0, top_k=0, top_p=0.0,
                      seed=5)
    flat.run_until_idle()
    t_ref, lp_ref = ref.result()[0], list(ref.log_probs)

    eng = ContinuousBatchingEngine(copy.deepcopy(cfg), toy_params, None,
                                   max_slots=4, num_pages=64, page_size=16,
                                   mesh=_pp_mesh(eight_devices, 2))
    req = eng.submit(prompt, 24, temperature=1.0, top_k=0, top_p=0.0,
                     seed=5)
    while len(req.generated) < 9:
        eng.step()
    assert eng.preempt(req)
    assert req._phase == "queued" and not req._pages
    eng.run_until_idle()
    assert req.result()[0] == t_ref
    np.testing.assert_allclose(list(req.log_probs), lp_ref, atol=5e-6)
    assert eng.preemptions == 1


# ---------------------------------------------------------------------------
# gating, inert flags, executable-cache geometry
# ---------------------------------------------------------------------------


def test_serve_params_gating(eight_devices):
    """serve_params builds exactly when the mesh has a pp extent; pp
    engines reject layouts the stage scan cannot serve."""
    cfg = _toy_cfg()
    assert pp_serve_mod.serve_params(cfg, None) is None
    mesh1 = ps.build_mesh(devices=eight_devices[:1])
    assert pp_serve_mod.serve_params(cfg, mesh1) is None
    mesh2 = _pp_mesh(eight_devices, 2)
    ppc = pp_serve_mod.serve_params(cfg, mesh2)
    assert ppc is not None and ppc.pp == 2
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    # num_layers must split evenly over the stages
    bad = _toy_cfg(num_layers=3)
    with pytest.raises(AssertionError):
        ContinuousBatchingEngine(bad, init_model_params(
            bad, jax.random.PRNGKey(0)), None, max_slots=4, num_pages=64,
            page_size=16, mesh=mesh2)
    # monolithic dense prefill has no stage decomposition
    with pytest.raises(AssertionError):
        ContinuousBatchingEngine(copy.deepcopy(cfg), params, None,
                                 max_slots=4, num_pages=64, page_size=16,
                                 prefill_chunk=0, mesh=mesh2)


def test_inert_flags_degrade_bitwise(eight_devices, toy_params):
    """A pp=1 mesh (flag set, one stage) builds no stage machinery and is
    BITWISE the no-mesh engine; --vocab_ring at tp=1 likewise resolves
    to None."""
    cfg = _toy_cfg()
    _, base = _run_engine(cfg, toy_params, None)
    mesh1 = ps.build_mesh(devices=eight_devices[:1])
    c_pp = copy.deepcopy(cfg)
    c_pp.parallel.pipeline_model_parallel_size = 1
    eng, one = _run_engine(c_pp, toy_params, mesh1)
    assert eng._ppc is None and eng._pp == 1
    for (t0, l0), (t1, l1) in zip(base, one):
        assert t0 == t1
        assert l0 == l1  # bitwise: no stages, no ring, no collectives
    c_vr = _toy_cfg(vocab_ring=True)
    assert ovl_mod.overlap_params(c_vr, mesh1) is None
    eng, vr1 = _run_engine(c_vr, toy_params, mesh1)
    assert not eng._vocab_ring
    for (t0, l0), (t1, l1) in zip(base, vr1):
        assert t0 == t1
        assert l0 == l1


def test_mesh_statics_pin_pp_and_vocab_ring_geometry(eight_devices,
                                                    toy_params):
    """Regression: pp / vocab-ring geometry lands in _mesh_statics so a
    pp engine never reuses a tp-only executable, and the tuple tail stays
    ("tp_overlap", mode) for the PR 15 key contract."""
    cfg = _toy_cfg()
    e_flat = ContinuousBatchingEngine(cfg, toy_params, None, max_slots=4,
                                      num_pages=64, page_size=16)
    assert e_flat._mesh_statics == (
        "mesh", None, "vocab_ring", "off", "tp_overlap", "off")
    mesh_tp2 = ps.build_mesh(tensor_model_parallel_size=2,
                             data_parallel_size=1,
                             devices=eight_devices[:2])
    mesh_pp2 = _pp_mesh(eight_devices, 2)
    e_tp = ContinuousBatchingEngine(_toy_cfg(tp=2), toy_params, None,
                                    max_slots=4, num_pages=64,
                                    page_size=16, mesh=mesh_tp2)
    e_pp = ContinuousBatchingEngine(copy.deepcopy(cfg), toy_params, None,
                                    max_slots=4, num_pages=64,
                                    page_size=16, mesh=mesh_pp2)
    # build_mesh materializes every axis: the shape tuple alone separates
    # a (pp=2, tp=1) engine from a (pp=1, tp=2) engine on the same chips
    assert e_tp._mesh_statics != e_pp._mesh_statics
    assert e_pp._mesh_statics != e_flat._mesh_statics
    assert dict(e_pp._mesh_statics[1])["pp"] == 2
    assert e_pp._mesh_statics[-2:] == ("tp_overlap", "off")
    # vocab_ring flips its own component without disturbing the tail
    e_vr = ContinuousBatchingEngine(_toy_cfg(tp=2, vocab_ring=True),
                                    toy_params, None, max_slots=4,
                                    num_pages=64, page_size=16,
                                    mesh=mesh_tp2)
    assert e_vr._vocab_ring
    assert e_vr._mesh_statics[2:4] == ("vocab_ring", "ring")
    assert e_tp._mesh_statics[2:4] == ("vocab_ring", "off")
    assert e_vr._mesh_statics[-2:] == ("tp_overlap", "off")
    assert e_vr._mesh_statics != e_tp._mesh_statics


# ---------------------------------------------------------------------------
# vocab-parallel head ring
# ---------------------------------------------------------------------------


def test_vocab_ring_hlo_and_numeric_parity(eight_devices, toy_params):
    """Mechanism, not vibes: the ring head program carries the
    vocab-ring-tp2 scope and a ppermute chain (>= 2*tp-2 hops), and its
    logits match the plain all-gather head within 1e-5."""
    from megatron_llm_tpu.models.language_model import (
        compute_logits, head_weight,
    )
    from megatron_llm_tpu.parallel.tp import param_shardings

    mesh = ps.build_mesh(tensor_model_parallel_size=2,
                         data_parallel_size=1, devices=eight_devices[:2])
    cfg_off = _toy_cfg(tp=2)
    cfg_vr = _toy_cfg(tp=2, vocab_ring=True)
    with ps.global_mesh(mesh):
        ovl = ovl_mod.overlap_params(cfg_vr, mesh)
        assert ovl is not None and ovl.vocab_ring and not ovl.ring_rows
        sharded = jax.device_put(toy_params,
                                 param_shardings(mesh, toy_params))
        x = jnp.asarray(np.random.RandomState(0).randn(3, 1, 64),
                        jnp.float32)

        def head(p, h):
            with ovl_mod.activate(ovl):
                return compute_logits(cfg_vr, p, h)

        hlo = jax.jit(head).lower(sharded, x).compile().as_text()
        assert ovl_mod.vocab_scope_name(2) in hlo, "ring scope missing"
        assert hlo.count("collective-permute") >= 2  # 2*tp - 2 hops
        assert head_weight(cfg_vr, sharded) is not None
        plain = jax.jit(
            lambda p, h: compute_logits(cfg_off, p, h))(sharded, x)
        ring = jax.jit(head)(sharded, x)
        np.testing.assert_allclose(np.asarray(plain), np.asarray(ring),
                                   atol=1e-5, rtol=1e-5)


def test_vocab_ring_engine_token_identity(eight_devices, toy_params):
    """--vocab_ring at tp=2: same greedy tokens as the plain tp engine
    (the head pays an all-gather-matmul ring every decode step; the
    tolerance-vs-bitwise story is the overlap.py chunked-GEMM one)."""
    mesh = ps.build_mesh(tensor_model_parallel_size=2,
                         data_parallel_size=1, devices=eight_devices[:2])
    _, off = _run_engine(_toy_cfg(tp=2), toy_params, mesh)
    eng, vr = _run_engine(_toy_cfg(tp=2, vocab_ring=True), toy_params,
                          mesh)
    assert eng._vocab_ring
    _check(off, vr, "vocab ring tp2")


def test_pp_tp_vocab_ring_compose(eight_devices, toy_params):
    """The full ISSUE 20 layout: pp=2 x tp=2 with the vocab ring on the
    head — greedy parity vs the flat single-chip engine."""
    cfg = _toy_cfg()
    _, base = _run_engine(cfg, toy_params, None)
    mesh = _pp_mesh(eight_devices, 2, tp=2)
    _, out = _run_engine(_toy_cfg(tp=2, vocab_ring=True), toy_params,
                         mesh)
    _check(base, out, "pp2 x tp2 + vocab ring")


# ---------------------------------------------------------------------------
# observables: span, scope, gauges
# ---------------------------------------------------------------------------


def test_pp_observables(eight_devices, toy_params):
    """engine-pp-tick span in a trace dump; stage-permute scope stamped
    on the compiled tick program; pp gauges report the stage geometry."""
    from megatron_llm_tpu.generation.engine import PagedState
    from megatron_llm_tpu.models.language_model import (
        make_rope_cache, model_forward,
    )
    from megatron_llm_tpu.observability import registry as obs_registry
    from megatron_llm_tpu.observability import trace as obs_trace

    cfg = _toy_cfg()
    tracer = obs_trace.configure()
    eng, _ = _run_engine(copy.deepcopy(cfg), toy_params,
                         _pp_mesh(eight_devices, 2))
    names = {e[1] for e in tracer.snapshot()}
    assert "engine-pp-tick" in names, sorted(names)
    obs_trace.disable()
    reg = obs_registry.get_registry()
    assert reg.gauge("mlt_engine_pp_stages").value == 2
    assert (reg.gauge("mlt_engine_kv_stage_bytes").value
            == eng.pool.kv_stage_bytes())

    # the stage-boundary ppermutes run under the stage-permute scope —
    # lower the engine's own tick forward and read the compiled program
    bt = np.zeros((eng.max_slots, eng.pages_per_seq), np.int32)
    pos = np.zeros((eng.max_slots,), np.int32)
    toks = np.full((eng.max_slots,), 2, np.int32)

    def tickish(params, pk, pv):
        rope = make_rope_cache(cfg)
        with pp_serve_mod.activate(eng._ppc):
            logits, _ = model_forward(
                cfg, params, jnp.asarray(toks)[:, None],
                position_ids=jnp.asarray(pos)[:, None], rope_cache=rope,
                kv_caches=(pk, pv),
                paged=PagedState(jnp.asarray(bt), jnp.asarray(pos)))
        return logits

    hlo = jax.jit(tickish).lower(
        eng.params, eng.pool.k, eng.pool.v).compile().as_text()
    assert pp_serve_mod.STAGE_PERMUTE_SCOPE in hlo, "stage scope missing"
    assert "collective-permute" in hlo
