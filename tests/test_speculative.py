"""Speculative decoding tests (ISSUE 9).

Gates: (1) greedy speculative decode is BITWISE identical (tokens and
log-probs, jnp fallback) to ``spec_k=0`` — for any draft, cache on/off,
across speculation depths, through stop-token truncation and through
preemption/resume; (2) sampled speculative decode matches the target
model's distribution: the acceptance rule passes a direct statistical
test against the theoretical emission law, and engine-level marginals
match non-speculative sampling; (3) the draft shares the page pool
correctly — one page id addresses both caches, refcounts drain whole,
admission accounting is unchanged; (4) per-slot adaptive depth shrinks
on low acceptance; (5) the telemetry surface (``mlt_engine_spec_*``,
``spec_stats``, ``/health``) is live.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from megatron_llm_tpu.generation import (
    ContinuousBatchingEngine,
    DraftModel,
)
from megatron_llm_tpu.generation.speculative import (
    check_draft_compat,
    extend_params_identity,
    speculative_acceptance,
)
from megatron_llm_tpu.generation.speculative.draft import parse_draft_spec

VOCAB = 67


@pytest.fixture(scope="module")
def models():
    """Target (2L), an independent random draft (1L, smaller), and an
    identity-extended target that provably agrees with a same-width
    draft."""
    from megatron_llm_tpu.models import init_model_params, make_config

    def mk(layers, hidden, heads, nkv, ffn):
        return make_config(
            "llama2", num_layers=layers, hidden_size=hidden,
            num_attention_heads=heads, num_attention_heads_kv=nkv,
            ffn_hidden_size=ffn, seq_length=128,
            max_position_embeddings=256, vocab_size=VOCAB,
            hidden_dropout=0.0, attention_dropout=0.0,
            params_dtype="float32", use_flash_attn=False,
        )

    cfg = mk(2, 64, 4, 2, 128)
    params = init_model_params(cfg, jax.random.PRNGKey(0))
    dcfg = mk(1, 32, 2, 2, 64)
    dparams = init_model_params(dcfg, jax.random.PRNGKey(1))
    # same-width 1-layer draft + target whose extra layer is an exact
    # identity: greedy acceptance is provably 100%
    acfg = mk(1, 64, 4, 2, 128)
    aparams = init_model_params(acfg, jax.random.PRNGKey(2))
    agree_params = extend_params_identity(acfg, aparams, cfg,
                                          jax.random.PRNGKey(3))
    return {
        "cfg": cfg, "params": params,
        "draft": DraftModel(dcfg, dparams),
        "agree_draft": DraftModel(acfg, aparams),
        "agree_params": agree_params,
    }


def _engine(cfg, params, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_seq", 128)
    return ContinuousBatchingEngine(cfg, params, None, **kw)


def _run(eng, jobs):
    reqs = [eng.submit(p, n, **kw) for p, n, kw in jobs]
    eng.run_until_idle()
    out = []
    for r in reqs:
        toks, lps = r.result(timeout=60)
        out.append((toks, lps))
    return out


def _greedy_jobs(n_new=18):
    shared = [2 + (i * 7) % 60 for i in range(48)]  # 3 full pages @ 16
    jobs = []
    for i in range(4):
        tail = [3 + (i * 11 + j) % 60 for j in range(3 + 9 * i)]
        jobs.append((shared + tail, n_new,
                     dict(top_k=1, termination_id=10 ** 9)))
    jobs.append(([5, 9, 2], n_new, dict(top_k=1, termination_id=10 ** 9)))
    # page-aligned full duplicates: the second takes the COW path
    jobs.append((list(shared), 10, dict(top_k=1, termination_id=10 ** 9)))
    jobs.append((list(shared), 10, dict(top_k=1, termination_id=10 ** 9)))
    return jobs


# ---------------------------------------------------------------------------
# Bitwise losslessness (greedy)
# ---------------------------------------------------------------------------


def test_greedy_spec_bitwise_vs_nonspec(models):
    """spec_k in {1, 3} with a draft the target almost never agrees with:
    the emitted stream must still be the greedy target stream, bitwise —
    tokens AND log-probs — including prefix-cache hits and COW."""
    cfg, params = models["cfg"], models["params"]
    jobs = _greedy_jobs()
    base = _engine(cfg, params, spec_k=0)
    res0 = []
    for j in jobs:  # submit one-by-one so later jobs hit the cache
        res0.extend(_run(base, [j]))
    for k in (1, 3):
        eng = _engine(cfg, params, spec_k=k, spec_draft=models["draft"])
        res = []
        for j in jobs:
            res.extend(_run(eng, [j]))
        for (t0, lp0), (t1, lp1) in zip(res0, res):
            assert t0 == t1, f"tokens diverged at spec_k={k}"
            assert lp0 == lp1, f"log-probs diverged at spec_k={k}"
        assert eng.spec_ticks > 0
        assert eng.cow_copies >= 1  # page-aligned duplicate took COW


def test_greedy_spec_bitwise_cache_off(models):
    cfg, params = models["cfg"], models["params"]
    jobs = _greedy_jobs()
    res0 = _run(_engine(cfg, params, spec_k=0, prefix_cache=False), jobs)
    res1 = _run(_engine(cfg, params, spec_k=3, prefix_cache=False,
                        spec_draft=models["draft"]), jobs)
    assert res0 == res1


def test_greedy_spec_bitwise_high_acceptance(models):
    """The agreeing draft accepts ~everything — the fast path (multi-token
    blocks, bonus tokens every tick) must be just as bitwise."""
    cfg = models["cfg"]
    params = models["agree_params"]
    jobs = _greedy_jobs()
    res0 = _run(_engine(cfg, params, spec_k=0), jobs)
    eng = _engine(cfg, params, spec_k=4, spec_draft=models["agree_draft"])
    res1 = _run(eng, jobs)
    assert res0 == res1
    stats = eng.spec_stats()
    assert stats["acceptance_rate"] == 1.0, stats
    # multi-token progress: far fewer ticks than emitted tokens
    assert eng.spec_emitted_tokens > 2 * eng.spec_ticks


def test_greedy_spec_stop_token_truncation(models):
    """A termination token landing mid-accepted-block must cut generation
    at exactly the position non-speculative decode stops at."""
    cfg, params = models["cfg"], models["params"]
    prompt = [5, 9, 2, 33, 17]
    probe = _run(_engine(cfg, params, spec_k=0),
                 [(prompt, 16, dict(top_k=1, termination_id=10 ** 9))])
    gen0 = probe[0][0][len(prompt):]
    stop = gen0[4]  # force a stop mid-stream (and mid-verify-block)
    jobs = [(prompt, 16, dict(top_k=1, termination_id=stop))]
    res0 = _run(_engine(cfg, params, spec_k=0), jobs)
    res1 = _run(_engine(cfg, params, spec_k=4,
                        spec_draft=models["agree_draft"],
                        spec_adaptive=False), jobs)
    assert res0 == res1
    assert res0[0][0][-1] == stop and len(res0[0][0]) < len(prompt) + 16


def test_greedy_spec_bitwise_under_preemption(models):
    """Preempt a speculating slot mid-decode (pages parked in the trie,
    draft pages released through the same path), resume, and the output
    must still be bitwise the non-speculative stream."""
    cfg, params = models["cfg"], models["params"]
    prompt = [2 + (j * 5) % 60 for j in range(40)]
    jobs = [(prompt, 20, dict(top_k=1, termination_id=10 ** 9))]
    res0 = _run(_engine(cfg, params, spec_k=0), jobs)

    eng = _engine(cfg, params, spec_k=3, spec_draft=models["draft"])
    req = eng.submit(*jobs[0][:2], **jobs[0][2])
    while req._phase != "decode" or len(req.generated) < 5:
        eng.step()
    assert eng.preempt(req), "request should be preemptible"
    assert req._phase == "queued" and not req._pages
    eng.run_until_idle()
    toks, lps = req.result(timeout=60)
    assert (toks, lps) == res0[0]
    assert req._preemptions == 1


# ---------------------------------------------------------------------------
# Sampled losslessness (distribution match)
# ---------------------------------------------------------------------------


def test_acceptance_rule_matches_target_distribution():
    """Drive :func:`speculative_acceptance` with synthetic p/q over a tiny
    vocab, many trials: the first emitted token's empirical distribution
    must match p_1, and the draft-acceptance rate must match the
    theoretical sum(min(p, q))."""
    rng = np.random.default_rng(0)
    v, K, n = 8, 3, 20000
    q_dist = rng.dirichlet(np.ones(v), size=K)          # [K, v]
    p_dist = rng.dirichlet(np.ones(v), size=K + 1)      # [K+1, v]

    # draft tokens sampled from q (position j uses q_dist[j])
    draft = np.stack(
        [rng.choice(v, size=n, p=q_dist[j]) for j in range(K)], axis=1)
    u = rng.random((n, K)).astype(np.float32)
    emit_keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(n))
    q_filt = jnp.log(jnp.asarray(q_dist, jnp.float32))[None].repeat(n, 0)
    t_filt = jnp.log(jnp.asarray(p_dist, jnp.float32))[None].repeat(n, 0)
    t_greedy = jnp.argmax(t_filt, axis=-1).astype(jnp.int32)
    accepted, counts, emit = jax.jit(speculative_acceptance)(
        jnp.asarray(draft, jnp.int32), q_filt, t_filt, t_greedy,
        jnp.zeros((n,), bool), jnp.full((n,), K, jnp.int32),
        jnp.asarray(u), emit_keys)
    accepted = np.asarray(accepted)
    emit = np.asarray(emit)

    # (a) first-draft acceptance rate == sum(min(p_1, q_1))
    theo = np.minimum(p_dist[0], q_dist[0]).sum()
    emp = float((accepted >= 1).mean())
    assert abs(emp - theo) < 0.02, (emp, theo)

    # (b) the emitted token at position 0 is distributed exactly as p_1
    # (accepted draft OR rejection-residual draw — the speculative
    # sampling theorem)
    first = emit[:, 0]
    emp_dist = np.bincount(first, minlength=v) / n
    tv = 0.5 * np.abs(emp_dist - p_dist[0]).sum()
    assert tv < 0.02, (tv, emp_dist, p_dist[0])

    # (c) k_eff masking: depth-0 rows emit exactly one token from p_1
    accepted0, counts0, emit0 = jax.jit(speculative_acceptance)(
        jnp.asarray(draft, jnp.int32), q_filt, t_filt, t_greedy,
        jnp.zeros((n,), bool), jnp.zeros((n,), jnp.int32),
        jnp.asarray(u), emit_keys)
    assert int(np.asarray(accepted0).max()) == 0
    assert np.all(np.asarray(counts0) == 1)
    tv0 = 0.5 * np.abs(
        np.bincount(np.asarray(emit0)[:, 0], minlength=v) / n
        - p_dist[0]).sum()
    assert tv0 < 0.02, tv0


def test_sampled_spec_marginals_match_nonspec(models):
    """Engine-level: the same sampled workload (top_k=5, many seeds)
    through spec and non-spec engines produces matching first-token
    marginals — and both match the target model's actual top-k=5
    distribution."""
    cfg, params = models["cfg"], models["params"]
    prompt = [7, 3, 29, 11]
    n, k_new = 320, 3

    def first_tokens(spec_k):
        kw = {} if not spec_k else dict(
            spec_k=spec_k, spec_draft=models["draft"])
        eng = _engine(cfg, params, max_slots=8, max_queue=0, **kw)
        reqs = [eng.submit(prompt, k_new, top_k=5, temperature=1.0,
                           seed=i, termination_id=10 ** 9)
                for i in range(n)]
        eng.run_until_idle()
        for r in reqs:
            r.result(timeout=120)
        return np.asarray([r.generated[0] for r in reqs])

    t0 = first_tokens(0)
    t1 = first_tokens(3)
    # same support (top-5 of the same logits row)
    assert set(t1) <= set(np.unique(t0)) | set(np.unique(t1))
    d0 = np.bincount(t0, minlength=VOCAB) / n
    d1 = np.bincount(t1, minlength=VOCAB) / n
    tv = 0.5 * np.abs(d0 - d1).sum()
    assert tv < 0.15, (tv, np.nonzero(d0)[0], np.nonzero(d1)[0])


# ---------------------------------------------------------------------------
# Pool / scheduling integration
# ---------------------------------------------------------------------------


def test_spec_pool_shares_page_ids_and_drains(models):
    cfg, params = models["cfg"], models["params"]
    eng = _engine(cfg, params, spec_k=3, spec_draft=models["draft"],
                  prefix_cache=False)
    pool = eng.pool
    assert pool.draft_k is not None
    # one page-id space: draft arrays have the same page axis
    assert pool.draft_k.shape[1] == pool.k.shape[1]
    assert pool.draft_k.shape[0] == models["draft"].cfg.model.num_layers
    _run(eng, _greedy_jobs())
    assert np.all(pool.refcounts == 0)
    assert pool.num_free == pool.num_pages - 1  # cache off: all pages back
    assert eng._committed == 0


def test_spec_requires_draft_and_chunked_prefill(models):
    cfg, params = models["cfg"], models["params"]
    with pytest.raises(ValueError, match="draft"):
        _engine(cfg, params, spec_k=2)
    with pytest.raises(AssertionError, match="chunked prefill"):
        _engine(cfg, params, spec_k=2, spec_draft=models["draft"],
                prefill_chunk=0)


def test_draft_compat_rejected(models):
    cfg = models["cfg"]
    from megatron_llm_tpu.models import make_config

    bad = make_config(
        "llama2", num_layers=1, hidden_size=32, num_attention_heads=2,
        num_attention_heads_kv=2, ffn_hidden_size=64, seq_length=128,
        max_position_embeddings=256, vocab_size=VOCAB + 1,
        params_dtype="float32", use_flash_attn=False)
    with pytest.raises(ValueError, match="vocab"):
        check_draft_compat(cfg, bad, max_seq=128)
    short = make_config(
        "llama2", num_layers=1, hidden_size=32, num_attention_heads=2,
        num_attention_heads_kv=2, ffn_hidden_size=64, seq_length=64,
        max_position_embeddings=64, vocab_size=VOCAB,
        params_dtype="float32", use_flash_attn=False)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        check_draft_compat(cfg, short, max_seq=128)


def test_parse_draft_spec():
    fam, ov, load = parse_draft_spec(
        "llama2:num_layers=2,hidden_size=256,use_flash_attn=false")
    assert fam == "llama2"
    assert ov == {"num_layers": 2, "hidden_size": 256,
                  "use_flash_attn": False}
    assert load is None
    fam, ov, load = parse_draft_spec("llama2:num_layers=1@/ckpt/d")
    assert load == "/ckpt/d" and ov == {"num_layers": 1}
    with pytest.raises(ValueError, match="key=val"):
        parse_draft_spec("llama2:num_layers")


def test_engine_resolves_draft_from_config_flags(models):
    """The server path: --spec_k/--spec_draft land in cfg.inference and
    the engine resolves the draft spec string itself (random-init branch),
    still bitwise-lossless vs spec_k=0."""
    import copy

    cfg = copy.deepcopy(models["cfg"])
    cfg.inference.spec_k = 2
    cfg.inference.spec_draft = (
        "llama2:num_layers=1,hidden_size=32,num_attention_heads=2,"
        "num_attention_heads_kv=2,ffn_hidden_size=64")
    eng = ContinuousBatchingEngine(cfg, models["params"], None,
                                   max_slots=2, max_seq=128)
    assert eng.spec_k == 2
    assert eng.draft_cfg.model.num_layers == 1
    assert eng.draft_cfg.model.vocab_size == VOCAB  # inherited from target
    jobs = [([4, 8, 15, 16], 10, dict(top_k=1, termination_id=10 ** 9))]
    res = _run(eng, jobs)
    base = _run(_engine(models["cfg"], models["params"], max_slots=2),
                jobs)
    assert res == base


def test_adaptive_depth_shrinks_on_low_acceptance(models):
    """The random draft accepts ~0: adaptive mode must collapse per-slot
    depth toward 1, spending far fewer draft tokens than fixed depth."""
    cfg, params = models["cfg"], models["params"]
    jobs = [([3, 1, 4, 1, 5], 24, dict(top_k=1, termination_id=10 ** 9))]
    fixed = _engine(cfg, params, spec_k=4, spec_draft=models["draft"],
                    spec_adaptive=False)
    _run(fixed, jobs)
    adaptive = _engine(cfg, params, spec_k=4, spec_draft=models["draft"],
                       spec_adaptive=True)
    reqs = [adaptive.submit(p, n, **kw) for p, n, kw in jobs]
    adaptive.run_until_idle()
    for r in reqs:
        r.result(timeout=60)
    assert adaptive.spec_draft_tokens < fixed.spec_draft_tokens
    assert reqs[0]._spec_ema < 0.5
    # losslessness is depth-independent: same tokens either way
    assert fixed.spec_emitted_tokens == adaptive.spec_emitted_tokens


def test_spec_under_slo_policy_preemption(models):
    """Scheduler-policy interaction: under the slo policy a hi-priority
    burst preempts speculating batch slots — draft pages release through
    the same trie-park path, and the preempted requests' outputs stay
    bitwise the plain-decode stream."""
    cfg, params = models["cfg"], models["params"]
    kw = dict(top_k=1, termination_id=10 ** 9)
    eng = _engine(cfg, params, max_slots=2, sched_policy="slo",
                  spec_k=3, spec_draft=models["draft"])
    lo = [eng.submit([2 + i] * 8, 40, priority=2, **kw) for i in range(2)]
    while sum(r._t_first > 0 for r in lo) < 2:
        eng.step()
    hi = [eng.submit([9, 9, 9 + i], 8, priority=0,
                     ttft_deadline_ms=60000.0, **kw) for i in range(2)]
    eng.run_until_idle()
    for r in hi:
        r.result(timeout=60)
    assert eng.preemptions >= 1
    base = _engine(cfg, params, max_slots=2)
    ref = [base.submit([2 + i] * 8, 40, **kw) for i in range(2)]
    base.run_until_idle()
    for a, b in zip(lo, ref):
        assert a.result(timeout=60) == b.result(timeout=60)


# ---------------------------------------------------------------------------
# Telemetry
# ---------------------------------------------------------------------------


def test_spec_metrics_and_health(models):
    cfg, params = models["cfg"], models["params"]
    from megatron_llm_tpu.generation.server import MegatronServer
    from megatron_llm_tpu.observability import registry as obs_registry

    obs_registry.set_publishing(True)
    try:
        eng = _engine(cfg, params, spec_k=2, spec_draft=models["draft"])
        _run(eng, _greedy_jobs(n_new=6)[:2])
        stats = eng.spec_stats()
        assert stats["enabled"] and stats["spec_k"] == 2
        assert stats["draft_tokens"] > 0
        assert stats["acceptance_rate"] is not None
        text = obs_registry.get_registry().render()
        for name in ("mlt_engine_spec_draft_tokens_total",
                     "mlt_engine_spec_accepted_tokens_total",
                     "mlt_engine_spec_acceptance_ratio",
                     "mlt_engine_spec_accepted_length",
                     "mlt_engine_spec_k"):
            assert name in text, f"{name} missing from /metrics"
        server = MegatronServer(eng)
        health = server.health()
        assert health["spec"]["enabled"] is True
        assert health["spec"]["spec_k"] == 2
        # a non-speculating engine reports spec disabled
        plain = _engine(cfg, params)
        assert MegatronServer(plain).health()["spec"] == {"enabled": False}
    finally:
        # restore the PROCESS DEFAULT (publishing on) — restoring False
        # left every later-ordered test with a dead registry (latent
        # order dependence, exposed by non-alphabetical test selection)
        obs_registry.set_publishing(True)
