"""Flash-attention numerics UNDER SHARDED MESHES (round-4 VERDICT item 3).

Round 4 certified the shard_map compositions by AOT *compile* only; these
tests run the Pallas kernel (interpret mode on the virtual CPU mesh — the
same kernel code paths, minus Mosaic codegen) through the real
``_flash_sharded`` dispatch wrappers and compare against ``xla_attention``:

  * dp x tp pjit path (the single shard_map over batch/heads)
  * nested-manual composition: enclosing {pp, cp}-manual shard_map (the
    pipeline engine's context) with the inner flash shard_map over
    dp/ep/tp — the exact structure of the (round-5 fixed) tp8 x pp8 x dp4
    north-star layout, including the backward kernels
  * GQA + causal + segment-ids variants on the sharded paths

A mis-sharded composition shows up as a numeric mismatch here (each shard
would compute attention over the wrong slice), not a compile error.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from megatron_llm_tpu.core import parallel_state as ps
from megatron_llm_tpu.parallel import compat
from megatron_llm_tpu.ops.attention import _flash_sharded, xla_attention
from megatron_llm_tpu.ops.attention import make_attention_bias


def _qkv(key, b=4, s=256, n=4, nkv=None, d=64, dtype=jnp.float32):
    nkv = nkv or n
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, n, d), dtype) * 0.3
    k = jax.random.normal(kk, (b, s, nkv, d), dtype) * 0.3
    v = jax.random.normal(kv, (b, s, nkv, d), dtype) * 0.3
    return q, k, v


def _ref(q, k, v, causal=True, segment_ids=None, sliding_window=None):
    bias = make_attention_bias(
        q.shape[1], k.shape[1], causal=causal, sliding_window=sliding_window,
        segment_ids_q=segment_ids, segment_ids_kv=segment_ids)
    return xla_attention(q, k, v, bias=bias, scale=1.0 / (q.shape[-1] ** 0.5))


@pytest.mark.parametrize("nkv,segmented", [(4, False), (2, False), (2, True)])
def test_flash_dp_tp_pjit_parity(eight_devices, nkv, segmented):
    """dp2 x tp2 pjit path, fwd + grads vs XLA attention."""
    mesh = ps.build_mesh(tensor_model_parallel_size=2, data_parallel_size=2,
                         devices=eight_devices[:4])
    q, k, v = _qkv(jax.random.PRNGKey(0), nkv=nkv)
    seg = None
    if segmented:
        seg = jnp.concatenate([jnp.zeros((4, 128), jnp.int32),
                               jnp.ones((4, 128), jnp.int32)], axis=1)

    with ps.global_mesh(mesh), mesh:
        qs = NamedSharding(mesh, P(("dp", "ep"), None, "tp", None))
        qp = jax.device_put(q, qs)
        kp = jax.device_put(k, NamedSharding(
            mesh, P(("dp", "ep"), None, None, None)))
        vp = jax.device_put(v, NamedSharding(
            mesh, P(("dp", "ep"), None, None, None)))

        def loss(q_, k_, v_):
            o = _flash_sharded(q_, k_, v_, seg, 1.0 / 8.0, None, 128, 128)
            return (o.astype(jnp.float32) ** 2).sum(), o

        (val, out), grads = jax.jit(
            jax.value_and_grad(loss, argnums=(0, 1, 2), has_aux=True)
        )(qp, kp, vp)

    def ref_loss(q_, k_, v_):
        o = _ref(q_, k_, v_, segment_ids=seg)
        return (o.astype(jnp.float32) ** 2).sum(), o

    (rval, rout), rgrads = jax.value_and_grad(
        ref_loss, argnums=(0, 1, 2), has_aux=True)(q, k, v)

    np.testing.assert_allclose(np.asarray(out), np.asarray(rout),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(float(val), float(rval), rtol=1e-5)
    for g, rg in zip(grads, rgrads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                   atol=3e-4, rtol=3e-4)


def test_flash_nested_manual_parity(eight_devices):
    """The pipeline composition: enclosing {pp, cp}-manual shard_map, inner
    flash shard_map over dp/ep/tp — dp2 x pp2 x tp2, the minimized
    north-star structure. Every pp shard sees the same (replicated)
    microbatch here, so the output must equal the unsharded reference; a
    wrong nested in_spec would feed each shard the wrong q/k/v slice."""
    mesh = ps.build_mesh(tensor_model_parallel_size=2,
                         pipeline_model_parallel_size=2,
                         data_parallel_size=2, devices=eight_devices)
    q, k, v = _qkv(jax.random.PRNGKey(1), b=4, s=256, n=4, nkv=2)

    with ps.global_mesh(mesh), mesh:
        def body(q_, k_, v_):
            o = _flash_sharded(q_, k_, v_, None, 1.0 / 8.0, None, 128, 128)
            # touch pp like the tick loop does (identity ppermute keeps
            # values comparable to the reference)
            perm = [(i, i) for i in range(2)]
            return jax.lax.ppermute(o, ps.PP_AXIS, perm)

        fn = compat.shard_map(
            body, mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
            axis_names={ps.PP_AXIS, ps.CP_AXIS}, check_vma=False)

        def loss(q_, k_, v_):
            o = fn(q_, k_, v_)
            return (o.astype(jnp.float32) ** 2).sum(), o

        (val, out), grads = jax.jit(
            jax.value_and_grad(loss, argnums=(0, 1, 2), has_aux=True)
        )(q, k, v)

    rout = _ref(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(rout),
                               atol=2e-5, rtol=2e-5)

    def ref_loss(q_, k_, v_):
        o = _ref(q_, k_, v_)
        return (o.astype(jnp.float32) ** 2).sum()

    rgrads = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, rg in zip(grads, rgrads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                   atol=3e-4, rtol=3e-4)


def test_flash_nested_manual_sliding_window(eight_devices):
    """Sliding-window masking survives the nested composition (Mistral
    family at the pipelined layouts)."""
    mesh = ps.build_mesh(tensor_model_parallel_size=2,
                         pipeline_model_parallel_size=2,
                         data_parallel_size=2, devices=eight_devices)
    q, k, v = _qkv(jax.random.PRNGKey(2), b=2, s=256, n=4, nkv=4)

    with ps.global_mesh(mesh), mesh:
        fn = compat.shard_map(
            lambda q_, k_, v_: _flash_sharded(
                q_, k_, v_, None, 1.0 / 8.0, 64, 128, 128),
            mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
            axis_names={ps.PP_AXIS, ps.CP_AXIS}, check_vma=False)
        out = jax.jit(fn)(q, k, v)

    rout = _ref(q, k, v, sliding_window=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(rout),
                               atol=2e-5, rtol=2e-5)
